"""Shared training driver — the `MutableModule.fit` analog.

Reference: the body of train_end2end.py::train_net (SURVEY.md §4.1): roidb
load → AnchorLoader → param init/resume → fit with metrics, Speedometer,
epoch checkpoints. All entry points (end2end, rpn-only, rcnn-only stages)
funnel through `fit_detector`.
"""

from __future__ import annotations

import itertools
import os
from typing import Callable, Dict, List, Optional, Union

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.datasets import dataset_from_config
from mx_rcnn_tpu.data.datasets.imdb import filter_roidb, merge_roidb
from mx_rcnn_tpu.data.feedguard import FeedGuard
from mx_rcnn_tpu.data.loader import AnchorLoader
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models.zoo import build_model, forward_train, init_params
from mx_rcnn_tpu.obs import (
    StallWatchdog,
    StepTimer,
    obs_from_config,
    run_meta_fields,
)
from mx_rcnn_tpu.obs import compile_track
from mx_rcnn_tpu.obs import costs as obs_costs
from mx_rcnn_tpu.obs.costs import CostTracker
from mx_rcnn_tpu.obs.profile import TraceController
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.resilience import (
    CoordinatedStop,
    FileKVStore,
    HealCarry,
    Healer,
    PreemptionExit,
    PreemptionGuard,
    Quorum,
    QuorumExcludedError,
    acquire_backend,
    host_tree_copy,
)
from mx_rcnn_tpu.resilience import chaos
from mx_rcnn_tpu.resilience import quorum as quorum_lib
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.checkpoint import (
    checkpoint_meta,
    latest_checkpoint,
    latest_epoch,
    load_checkpoint,
    save_checkpoint,
)
from mx_rcnn_tpu.train.metrics import MetricBag
from mx_rcnn_tpu.train.optimizer import build_optimizer, rebase_schedule_count
from mx_rcnn_tpu.train.step import create_train_state, make_train_step


def load_gt_roidbs(cfg: Config, image_set: Optional[str] = None,
                   flip: Optional[bool] = None) -> List[Dict]:
    """'07_trainval+12_trainval'-style multi-set load (reference:
    rcnn/utils/load_data.py::load_gt_roidb + merge_roidb)."""
    image_set = image_set or cfg.dataset.image_set
    flip = cfg.train.flip if flip is None else flip
    roidbs = []
    for s in image_set.split("+"):
        ds = dataset_from_config(cfg.dataset, s)
        roidb = ds.gt_roidb()
        if flip:
            roidb = ds.append_flipped_images(roidb)
        roidbs.append(roidb)
    return filter_roidb(merge_roidb(roidbs))


def _dispatch_batches(loader, multi: int):
    """Group the loader stream into multi-step-dispatch super-batches:
    K consecutive batches stacked on a NEW leading step axis (leaves
    (K, B, ...) — train/step.py scans one optimizer step per row). K=1
    passes batches through untouched. A trailing partial group is dropped
    (logged by fit_detector) — an epoch boundary effect only."""
    if multi <= 1:
        yield from loader
        return
    group = []
    for batch in loader:
        group.append(batch)
        if len(group) == multi:
            yield {k: np.stack([b[k] for b in group]) for k in group[0]}
            group = []


def fit_detector(
    cfg: Config,
    roidb: List[Dict],
    prefix: str,
    begin_epoch: int = 0,
    end_epoch: Optional[int] = None,
    frequent: int = 20,
    resume: Union[bool, str] = False,
    pretrained_params=None,
    pretrained_npz: Optional[str] = None,
    mesh_spec: Optional[str] = None,
    seed: int = 0,
    epoch_callback: Optional[Callable] = None,
    forward_fn=None,
    loader_factory: Optional[Callable] = None,
    fixed_param_patterns=None,
    checkpoint_period: int = 1,
):
    """Train loop. Returns the final (host) params tree.

    forward_fn selects the training graph (end2end default; rpn-only /
    rcnn-only for the alternate stages); loader_factory builds the data
    iterator (AnchorLoader default, ROIIter for Fast R-CNN);
    fixed_param_patterns extends the frozen set (alternate stages 4/6 freeze
    the shared conv trunk — reference train_alternate.py).

    resume: True resumes from the latest EPOCH-BOUNDARY checkpoint under
    prefix (the pre-graftguard contract); "auto" also considers graftguard
    emergency (dispatch-tagged) saves and resumes from the most-advanced
    point, skipping the already-trained dispatch prefix of the interrupted
    epoch (the skipped batches are still loaded and discarded — host work
    only, bounded by one epoch). Resume is bit-exact vs an uninterrupted
    run: the epoch batch order is a pure function of (seed, epoch)
    (AnchorLoader.set_epoch) and each dispatch's rng key is derived from
    its global index (fold_in), not from a run-position-dependent split
    chain.

    graftguard (cfg.resilience; runbook OUTAGES.md): the backend is
    acquired through classified retry-with-backoff before the first
    device touch, and SIGTERM/SIGINT are honored at the next step
    boundary — emergency checkpoint (resilience.preempt_save), `preempt`
    event, then PreemptionExit carrying RESUMABLE_RC (75).

    graftheal (resilience/heal.py; resilience.heal, default on): a
    TRANSIENT step-time backend loss no longer kills the run — the loop
    captures the last known-good host state in memory, re-acquires the
    backend under resilience.backend_deadline_s, rebuilds the session
    (mesh, partition specs, flat buffers re-cut) and continues, emitting
    a `heal` event. If the backend returns with fewer devices the data
    axis is re-cut to the largest batch-divisible size — the GLOBAL
    batch is invariant, so the loader stream, LR schedule and loss
    trajectory carry straight across the shrink. Checkpoints carry a
    topology sidecar (graft_meta.json) so `--resume auto` onto a
    DIFFERENT device count recomputes the dispatch skip through the
    images-consumed invariant.

    With train.async_checkpoint (default, single-process) the epoch-end
    save is enqueued, not durable, when epoch_callback runs — a callback
    that READS the just-saved checkpoint from disk must not assume it has
    landed (it is durable by the next epoch's save and before return).

    epoch_callback(epoch, state, bag): with train.flat_params the state is
    a FlatTrainState — `.step` and `.params` (host-owned copies) keep
    working, but there is no `.opt_state` tree; use the checkpoint for
    optimizer inspection. A graftheal recovery that lands inside the
    epoch-end window REPLAYS it (event, save, callback) rather than
    dropping it — callbacks should tolerate a rare re-invocation for
    the same epoch.
    """
    from mx_rcnn_tpu.parallel.distributed import (
        is_primary,
        local_data_shards,
        process_count,
        process_index,
    )
    from mx_rcnn_tpu.train import precision

    # graftcast: resolve (and validate, loudly, before any device work)
    # the run's compute-dtype policy — threaded into run_meta and the
    # cost tracker so every MFU downstream divides by the right peak.
    policy = precision.policy_of(cfg)
    end_epoch = end_epoch or cfg.train.end_epoch
    # graftscope sink FIRST (it touches no jax): backend acquisition below
    # wants somewhere to emit backend_retry/backend_up events, so an
    # outage ridden out here leaves a structured record, not a watch log.
    obs_log = obs_from_config(cfg, default_dir=f"{prefix}.obs")
    # graftpulse flight recorder: every emitted record also lands in a
    # last-K in-memory ring, dumped to <obs dir>/flight_<reason>.json on
    # anomaly/stall/heal/preempt/crash — attached before backend
    # acquisition so even startup retries ride the ring.
    recorder = None
    if obs_log.enabled:
        from mx_rcnn_tpu.obs.health import FlightRecorder

        recorder = FlightRecorder(os.path.dirname(obs_log.path),
                                  capacity=cfg.obs.flight_events)
        obs_log.attach_ring(recorder)
    if cfg.resilience.backend_acquire:
        # Classified retry-with-backoff before the first device touch —
        # a transient relay outage (the TPU_OUTAGE_r5 signature) delays
        # the run instead of killing it (resilience/backend.py).
        acquire_backend(cfg.resilience, elog=obs_log)
    mesh = create_mesh(mesh_spec or cfg.mesh.mesh_shape)
    n_data = mesh.shape["data"]
    # Each process feeds only its own slice of the data axis (multi-host:
    # parallel/distributed.py; single-process: n_local == n_data).
    n_local = local_data_shards(mesh)
    # The run's NOMINAL footprint — what graftheal's elastic re-shard
    # derives the post-loss mesh from (parallel/partition.py).
    d0, m0 = n_data, mesh.shape["model"]
    logger.info("mesh: %s (data=%d model=%d, %d local shards)",
                mesh.devices.shape, n_data, mesh.shape["model"], n_local)

    if fixed_param_patterns is not None:
        from dataclasses import replace as _replace
        cfg = cfg.with_updates(network=_replace(
            cfg.network,
            fixed_param_patterns=tuple(cfg.network.fixed_param_patterns)
            + tuple(fixed_param_patterns)))

    model = build_model(cfg, mesh=mesh)  # mesh: ring attention for ViTDet
    params = pretrained_params or init_params(
        model, cfg, jax.random.PRNGKey(seed))
    if pretrained_npz:
        # ImageNet manifest init (reference: load_param over .params —
        # utils/pretrained.py). Trunk leaves come from the npz; the new
        # heads keep the fresh init above.
        from mx_rcnn_tpu.utils.pretrained import import_pretrained
        params, _ = import_pretrained(pretrained_npz, params)
    # Gradient accumulation: the step consumes accum x batch_images images
    # per optimizer step (train/step.py micro-step scan), so the LOADER
    # yields that much; the model/step cfg keeps the per-micro-step size.
    accum = max(1, cfg.train.grad_accum_steps)
    loader_cfg = cfg
    if accum > 1:
        from dataclasses import replace as _replace
        loader_cfg = cfg.with_updates(train=_replace(
            cfg.train, batch_images=cfg.train.batch_images * accum))
        if cfg.image.canvas_pack and not cfg.image.canvas_images:
            # graftcanvas × grad accum: planes stay one per MICRO-step
            # (images per plane = the un-accumulated batch) so the
            # step's accum reshape slices whole planes per chunk.
            loader_cfg = loader_cfg.with_updates(image=_replace(
                loader_cfg.image, canvas_images=cfg.train.batch_images))
        elif (cfg.image.canvas_pack
              and cfg.train.batch_images % cfg.image.canvas_images):
            # A user-set canvas_images that doesn't divide the MICRO
            # batch would pass the loader's validate (which sees the
            # accumulated batch) and then die as an opaque reshape error
            # inside the jitted accum split — fail loudly here instead.
            raise ValueError(
                f"image.canvas_images={cfg.image.canvas_images} must "
                f"divide the un-accumulated train.batch_images="
                f"{cfg.train.batch_images} under grad_accum_steps="
                f"{accum}: each micro-step must consume whole planes")
    if cfg.image.canvas_pack:
        # Fail fast (cfg-contract): surface a mis-sized canvas or an
        # unsupported family here, before prefetch workers spin up (the
        # loader validates too, but a worker-thread raise is noisier).
        from mx_rcnn_tpu.data.canvas import validate_canvas_pack

        validate_canvas_pack(loader_cfg)

    # graftfeed (data/feedguard.py; cfg.data): ONE guard per run, built
    # before the first loader and shared across every heal-time /
    # elastic rebuild below — the quarantine set and worker-death budget
    # are run-scoped, not loader-scoped. Chaos comes up here too (not at
    # the session loop) because the input plane has injection sites of
    # its own now.
    chaos_spec = chaos.from_env()
    feed_guard = FeedGuard(
        cfg.data, n_records=len(roidb), seed=seed,
        elog=obs_log if obs_log.enabled else None,
        quarantine_path=(os.path.join(os.path.dirname(obs_log.path),
                                      "quarantine.jsonl")
                         if obs_log.enabled else ""),
        # --resume / --resume auto re-applies the interrupted run's
        # quarantine file, so the resumed stream sees the SAME
        # substitutions at the same positions (bit-exact parity).
        resume=bool(resume),
        chaos_spec=chaos_spec if chaos_spec.active else None)

    def _build_loader(n_shards: int):
        """Loader for ``n_shards`` data shards. Factored out because the
        session loop rebuilds it under ``resilience.elastic_mode=rescale``
        (the global batch scales with the surviving fleet). Data sharding
        stays on RAW ``jax.process_count``/``process_index`` on purpose:
        the graftquorum simulated hosts override coordination identity
        only, and each sim process must load the full global batch to
        keep trajectories bit-identical (parallel/distributed.py)."""
        if loader_factory is None:
            return AnchorLoader(roidb, loader_cfg, num_shards=n_shards,
                                seed=seed,
                                process_count=jax.process_count(),
                                process_index=jax.process_index(),
                                guard=feed_guard)
        import inspect

        params_of = inspect.signature(loader_factory).parameters
        if "process_count" in params_of or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params_of.values()):
            return loader_factory(roidb, loader_cfg, n_shards,
                                  process_count=jax.process_count(),
                                  process_index=jax.process_index())
        if jax.process_count() > 1:
            raise ValueError(
                "loader_factory must accept process_count/process_index "
                "kwargs to run multi-host")
        return loader_factory(roidb, loader_cfg, n_shards)

    loader = _build_loader(n_local)
    steps_per_epoch = max(len(loader), 1)

    # Global images per dispatch — the run's INVARIANT unit of progress.
    # graftheal keeps it fixed across an elastic shrink (the surviving
    # devices carry more rows each), and the checkpoint meta sidecar
    # records it so a resume onto a different topology can convert a
    # dispatch tag minted under another mesh (see below).
    multi = max(1, cfg.train.multi_step_dispatch)
    ipd = cfg.train.batch_images * accum * n_data * multi
    disp_per_epoch = max(1, steps_per_epoch // multi)
    if multi > 1 and len(loader) % multi:
        logger.warning(
            "multi_step_dispatch=%d drops %d trailing batch(es) per epoch "
            "(loader yields %d)", multi, len(loader) % multi, len(loader))

    # Resume discovery BEFORE building the optimizer: a restored opt_state
    # carries optax's schedule counter; without one the LR schedule is
    # offset by begin_step instead (never both — that would double-count).
    # resume=True sees epoch-boundary checkpoints only; resume="auto"
    # (graftguard) also picks up dispatch-tagged emergency saves and
    # restarts mid-epoch from the most-advanced point.
    resume_epoch = resume_dispatch = None
    if resume == "auto":
        found = latest_checkpoint(prefix)
        if found is not None:
            resume_epoch, resume_dispatch = found
    elif resume:
        resume_epoch = latest_epoch(prefix)
    skip_dispatch = resume_dispatch or 0
    opt_state = None
    if resume_epoch is not None:
        begin_epoch = resume_epoch
        tx_tmpl = build_optimizer(cfg, params, steps_per_epoch)
        params, opt_state = load_checkpoint(
            prefix, resume_epoch, dispatch=resume_dispatch,
            template={"params": params},
            opt_state_template=tx_tmpl.init(params),
            means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
            num_classes=cfg.dataset.num_classes)
        # Elastic resume (graftheal): the meta sidecar records the SAVING
        # run's topology. When it differs from this run's, two
        # conversions apply — to boundary checkpoints and dispatch-0
        # emergency saves just as much as to mid-epoch ones:
        meta = checkpoint_meta(prefix, resume_epoch, resume_dispatch)
        old_ipd = (meta or {}).get("images_per_dispatch")
        if old_ipd and old_ipd != ipd:
            if skip_dispatch:
                # (1) A dispatch tag counts dispatches AT THE SAVING
                # RUN'S global batch — convert through the invariant,
                # images consumed, so the trained prefix of the epoch is
                # skipped exactly (floor: a non-divisible remainder
                # re-trains up to one old dispatch rather than skipping
                # unseen images).
                images_done = skip_dispatch * int(old_ipd)
                skip_dispatch = images_done // ipd
                logger.warning(
                    "elastic resume: checkpoint was saved at %d images/"
                    "dispatch (device_count=%s), this run dispatches %d — "
                    "skip recomputed to %d dispatch(es) (%d of %d images"
                    "%s)", old_ipd, (meta or {}).get("device_count", "?"),
                    ipd, skip_dispatch, skip_dispatch * ipd, images_done,
                    "" if images_done % ipd == 0 else
                    f"; {images_done % ipd} image(s) re-trained")
            if opt_state is not None:
                # (2) The restored schedule/Adam counters are in the
                # SAVING run's step units; this run counts against ITS
                # steps_per_epoch and schedule — rebase, or every
                # warmup/decay read happens at the old run's position
                # (train/optimizer.py).
                opt_state = rebase_schedule_count(
                    opt_state,
                    begin_epoch * steps_per_epoch + skip_dispatch * multi)
                logger.warning(
                    "elastic resume: optimizer counters rebased to step "
                    "%d (this run's units)",
                    begin_epoch * steps_per_epoch + skip_dispatch * multi)
        logger.info("resumed from %s epoch %d%s (opt_state %s)", prefix,
                    resume_epoch,
                    f" dispatch {resume_dispatch}"
                    if resume_dispatch is not None else "",
                    "restored" if opt_state is not None
                    else "reinitialized")

    # The session carry: host-side (params, opt_state, position) every
    # device-facing object is (re)built from — initially the fresh/
    # resumed state above, then whatever graftheal captured. opt_state
    # None => fresh slots, LR schedule offset by begin_step instead.
    carry = HealCarry(params=params, opt_state=opt_state,
                      epoch=begin_epoch, dispatch=skip_dispatch)

    # graftscope telemetry (mx_rcnn_tpu/obs): the sink was opened at the
    # top of this function (backend acquisition emits through it); a
    # no-op unless cfg.obs.enabled — nothing added to the hot loop.
    watchdog = tracer = cost_tracker = None
    if obs_log.enabled:
        obs_log.emit("run_meta", **run_meta_fields(
            cfg, mesh=mesh, prefix=prefix, batch_size=ipd,
            steps_per_epoch=steps_per_epoch, begin_epoch=begin_epoch,
            end_epoch=end_epoch, grad_accum=accum,
            multi_step_dispatch=multi, compute_dtype=policy.short))
        if cfg.obs.track_compiles:
            compile_track.activate(obs_log)
        # graftprof: trace windows (obs.trace_at_step counts dispatches
        # completed THIS process — also stall-armed by the watchdog) and
        # per-shape-bucket XLA cost events for the computed MFU.
        tracer = TraceController(
            obs_log, os.path.join(os.path.dirname(obs_log.path), "trace"),
            trace_at_step=cfg.obs.trace_at_step,
            trace_steps=cfg.obs.trace_steps)
        if cfg.obs.cost_analysis:
            # dtype-aware peak: a bf16 step graded against the f32 peak
            # would report ~2x the honest MFU (obs/costs.py).
            cost_tracker = CostTracker(obs_log,
                                       compute_dtype=policy.short)
        if cfg.obs.watchdog:
            watchdog = StallWatchdog(
                obs_log, stall_factor=cfg.obs.stall_factor,
                min_stall_s=cfg.obs.stall_min_s,
                poll_s=cfg.obs.watchdog_poll_s, tracer=tracer,
                recorder=recorder,
                heartbeat_every_s=cfg.obs.heartbeat_every_s)
            watchdog.start()
    timer = StepTimer(obs_log, watchdog=watchdog,
                      enrich=obs_costs.step_fields if obs_log.enabled
                      else None)
    speedometer = Speedometer(ipd, frequent, event_log=obs_log)

    # graftquorum (resilience/quorum.py): the coordination layer every
    # multi-host resilience path below rides. A preempted fleet drains to
    # ONE agreed dispatch boundary before the leader publishes; a healed
    # fleet agrees the post-heal topology and rebuilds in lockstep. The
    # store is jax.distributed's KV service on a real pod, or a shared
    # filesystem directory (resilience.quorum_store_dir) — which is also
    # how the N-process CPU tests exercise the real protocol.
    n_hosts = process_count()
    quorum = stopper = None
    if n_hosts > 1:
        if cfg.resilience.quorum_store_dir:
            store = FileKVStore(cfg.resilience.quorum_store_dir)
        else:
            client = quorum_lib.jax_kv_client()
            store = (quorum_lib.JaxKVStore(client)
                     if client is not None else None)
        if store is None:
            logger.warning(
                "graftquorum: no KV store reachable (jax.distributed not "
                "initialized and resilience.quorum_store_dir unset) — "
                "multi-host coordination disabled; preemption and heal "
                "fall back to uncoordinated per-host behavior")
        else:
            quorum = Quorum(
                store, process_index(), n_hosts,
                timeout_s=cfg.resilience.quorum_timeout_s,
                min_fraction=cfg.resilience.quorum_min_fraction,
                # grafttower: every barrier leaves a typed `barrier`
                # event in this host's stream (wait attribution + the
                # fleet fold's clock-skew correction signal). Host-side
                # only — no device work rides a barrier.
                elog=obs_log if obs_log.enabled else None)
            stopper = CoordinatedStop(quorum)
            logger.info(
                "graftquorum: host %d/%d coordinating via %s",
                process_index(), n_hosts,
                "filesystem store" if cfg.resilience.quorum_store_dir
                else "jax.distributed KV client")

    # Async epoch-end saves (train/checkpoint.py CheckpointWriter); the
    # multi-host primary-only pattern needs the synchronous path (orbax's
    # cross-process commit barrier would hang with one caller).
    writer = None
    if cfg.train.async_checkpoint and n_hosts > 1:
        # LOUD fallback (graftquorum satellite): silently dropping the
        # requested async writer made multi-host epoch ends mysteriously
        # slower than single-host. One structured `checkpoint` record
        # with fallback="sync" says what happened and why.
        logger.warning(
            "train.async_checkpoint requested but process_count()=%d: "
            "falling back to SYNCHRONOUS epoch saves (the async writer "
            "cannot satisfy orbax's cross-process commit barrier under "
            "the primary-only save pattern)", n_hosts)
        if obs_log.enabled:
            obs_log.emit("checkpoint", fallback="sync",
                         reason="multi-host: async writer incompatible "
                                "with primary-only saves",
                         hosts=n_hosts)
    elif cfg.train.async_checkpoint:
        from mx_rcnn_tpu.train import flatcore as _flatcore

        if (_flatcore.flat_mode_for(cfg)
                and jax.default_backend() == "cpu"):
            # Flat mode on the CPU backend: the background tensorstore
            # write racing the flat step's large host-buffer churn
            # (113+ MB donated buffers and backward concatenates every
            # step) crashes in the native allocator — reproduced as
            # free(): invalid pointer under MALLOC_CHECK_ with flat+async
            # only; tree+async and flat+sync run clean. On TPU the step's
            # buffers live in HBM, not host malloc, so async stays on.
            # (flat_mode_for without params is the cfg-level routing —
            # the rare TP-spec downgrade inside the session would only
            # make this choice conservative, never unsafe.)
            logger.info("flatcore on CPU backend: epoch checkpoints go "
                        "synchronous (async writer would race the flat "
                        "step's host allocator)")
        else:
            from mx_rcnn_tpu.train.checkpoint import CheckpointWriter

            writer = CheckpointWriter()

    # graftguard preemption (resilience/preempt.py): the handlers only
    # RECORD the signal; the loop below honors it at step boundaries.
    # install() is a no-op off the main thread (the guard stays inert).
    guard = None
    if cfg.resilience.preempt_handlers:
        guard = PreemptionGuard()
        guard.install()

    # graftheal (resilience/heal.py): a transient step-time backend loss
    # is recovered IN-PROCESS — capture the last known-good host state,
    # tear down + re-acquire the backend under the deadline, rebuild the
    # session (possibly on fewer devices) and continue. The initial
    # fallback is a host-owned copy of the starting state, refreshed by
    # periodic snapshots and by every successful capture.
    healer = None
    if cfg.resilience.heal and n_hosts > 1 and quorum is None:
        # Multi-host heal NEEDS the quorum: one process tearing its
        # backend down mid-collective wedges the others unless every
        # survivor re-converges on an agreed post-heal topology. Without
        # a reachable KV store, stay inert — preemption + --resume auto
        # still covers the fleet case.
        logger.warning("resilience.heal under process_count()=%d needs "
                       "graftquorum coordination but no KV store is "
                       "reachable; heal disabled", n_hosts)
    elif cfg.resilience.heal:
        healer = Healer(cfg.resilience, elog=obs_log, watchdog=watchdog,
                        recorder=recorder)
        healer.set_fallback(HealCarry(
            params=host_tree_copy(carry.params),
            opt_state=host_tree_copy(carry.opt_state),
            epoch=carry.epoch, dispatch=carry.dispatch))
        if quorum is not None:
            from mx_rcnn_tpu.parallel.partition import elastic_mesh_spec

            heal_generation = itertools.count()

            def _heal_quorum(devices):
                """graftquorum heal round, run INSIDE Healer.recover
                right after this host re-acquired its backend: survivors
                rendezvous under the deadline, the leader seals the
                post-heal topology from the MINIMUM surviving capacity,
                and a host that misses the round is excluded (it raises
                QuorumExcludedError out of recover — caught below and
                turned into a resumable exit)."""
                outcome = quorum.heal_round(
                    next(heal_generation), len(devices),
                    lambda n_dev, n_arrived: elastic_mesh_spec(
                        d0, m0, n_dev, cfg.train.batch_images * n_data,
                        mode=cfg.resilience.elastic_mode))
                if obs_log.enabled:
                    obs_log.emit("quorum", kind="heal",
                                 generation=outcome.generation,
                                 hosts=sorted(outcome.arrived),
                                 excluded=sorted(outcome.excluded),
                                 devices=outcome.devices,
                                 spec=outcome.spec)
                return outcome

            healer.quorum_hook = _heal_quorum

    # Per-session device-facing objects, (re)assigned by the session loop
    # below; declared here so the closures and the return path see them.
    state = flat_core = bag = None
    pos = (carry.epoch, carry.dispatch)
    # Coordinated-stop latch: this host has published its preemption
    # request to the quorum (at most one request per run — the agreed
    # boundary is cached by CoordinatedStop.check thereafter).
    stop_requested = False

    def _ckpt_meta(at_epoch: int, at_dispatch: Optional[int],
                   hosts=None):
        """The topology sidecar (train/checkpoint.py::META_NAME): what a
        dispatch WAS when this checkpoint was cut, so an elastic resume
        can convert the tag (see the skip recompute above). Multi-host
        runs also record the PARTICIPATING host set against the expected
        count — latest_checkpoint refuses an emergency save whose host
        set is incomplete (a torn save: some host died before reaching
        the publication barrier)."""
        meta = {"epoch": at_epoch, "dispatch": at_dispatch,
                "images_per_dispatch": ipd,
                "steps_per_epoch": steps_per_epoch,
                "device_count": int(mesh.devices.size),
                "mesh": {a: int(s) for a, s in
                         zip(mesh.axis_names, mesh.devices.shape)}}
        if n_hosts > 1:
            active = (quorum.active if quorum is not None
                      else range(n_hosts))
            meta["host_count"] = len(tuple(active))
            meta["hosts"] = sorted(hosts if hosts is not None else active)
        return meta

    def _capture() -> HealCarry:
        """graftheal's in-memory emergency capture: the live train state
        as host-OWNED tree-form copies (np.array, never device views —
        the backend they came from is about to be torn down), tagged
        with its position and the drained metric sums."""
        if state is None:
            raise RuntimeError("no live state to capture yet")
        if flat_core is not None:
            cap_params, cap_opt = flat_core.tree_state(state)
        else:
            cap_params = host_tree_copy(state.params)
            cap_opt = host_tree_copy(state.opt_state)
        if sched_begin:
            # This session's optimizer was built FRESH with its schedule
            # offset by begin_step, so its counters are session-relative
            # (they started at 0 mid-run). The carry contract is
            # ABSOLUTE counters — rebuilds use begin_step=0 whenever an
            # opt_state is present — so normalize to the capture
            # position (== sched_begin + updates this session).
            cap_opt = rebase_schedule_count(
                cap_opt, pos[0] * steps_per_epoch + pos[1] * multi)
        return HealCarry(params=cap_params, opt_state=cap_opt,
                         epoch=pos[0], dispatch=pos[1],
                         bag=bag.snapshot() if bag is not None else None)

    def _honor_preemption(at_epoch: int, at_dispatch: Optional[int],
                          need_save: bool = True):
        """Orderly preemption exit: emergency checkpoint (sync — it must
        be durable before the process dies), `preempt` event, then
        PreemptionExit carrying the resumable rc. at_dispatch=None marks
        an epoch boundary (at_epoch epochs complete).

        Multi-host (graftquorum): every host drained to the agreed stop
        boundary before getting here, and the fleet BARRIERS before the
        leader publishes — so the one emergency save is cut from a state
        every participant reached, and its meta records exactly who was
        still alive (`hosts`). A host missing from that set marks the
        save torn; latest_checkpoint skips it on resume."""
        arrived = None
        if quorum is not None:
            arrived = quorum.barrier("preempt/stop")
            if obs_log.enabled:
                obs_log.emit("quorum", kind="preempt",
                             hosts=sorted(arrived),
                             excluded=sorted(quorum.active - arrived),
                             agreed=[at_epoch, at_dispatch])
        saved = None
        if need_save and cfg.resilience.preempt_save and is_primary():
            if flat_core is not None:
                save_params, save_opt = flat_core.tree_state(state)
            else:
                save_params, save_opt = state.params, state.opt_state
            saved = save_checkpoint(
                prefix, at_epoch, save_params, save_opt,
                means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
                num_classes=cfg.dataset.num_classes, dispatch=at_dispatch,
                meta=_ckpt_meta(at_epoch, at_dispatch, hosts=arrived))
        # signum is None on a host that was never signaled itself but is
        # draining to the fleet's agreed boundary (coordinated stop).
        signum = guard.signum if guard is not None else None
        if obs_log.enabled:
            obs_log.emit("preempt", signal=signum,
                         step=(at_epoch * steps_per_epoch
                               + (at_dispatch or 0) * multi),
                         saved=saved)
        if recorder is not None:
            recorder.dump("preempt")
        logger.warning("preempted (signal %s) at epoch %d dispatch %s — "
                       "exiting rc %d; restart with --resume auto",
                       signum, at_epoch, at_dispatch,
                       PreemptionExit().code)
        raise PreemptionExit(signum)

    # graftpulse (obs/health.py + train/health.py): with obs on and
    # obs.health_every > 0 the step returns an extra in-graph numerics
    # output (same executable, no added per-step sync); the monitor
    # folds it into `health` events at the cadence and turns anomalies
    # into action — anomaly event, trace window, emergency checkpoint
    # of the last known-good state, flight dump, then NumericsAnomaly
    # under the default health_action=abort (resume with --resume auto).
    monitor = None
    health_on = obs_log.enabled and cfg.obs.health_every > 0
    if health_on:
        from mx_rcnn_tpu.obs.health import HealthMonitor

        def _save_good(good):
            """Emergency checkpoint of the monitor's known-good carry —
            the graftguard dispatch-tagged shape, so `--resume auto`
            picks it up like any preemption save."""
            if not is_primary():
                return None
            return save_checkpoint(
                prefix, good.epoch, good.params, good.opt_state,
                means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
                num_classes=cfg.dataset.num_classes,
                dispatch=good.dispatch,
                meta=_ckpt_meta(good.epoch, good.dispatch))

        monitor = HealthMonitor(
            obs_log, every=cfg.obs.health_every,
            window=cfg.obs.health_window,
            grad_factor=cfg.obs.health_grad_factor,
            loss_z=cfg.obs.health_loss_z,
            action=cfg.obs.health_action,
            tracer=tracer, recorder=recorder,
            capture=_capture if cfg.obs.health_checkpoint else None,
            save=_save_good if cfg.obs.health_checkpoint else None)

    try:
        while True:  # one iteration per backend session; graftheal re-enters
            try:
                state = flat_core = bag = None
                pos = (carry.epoch, carry.dispatch)
                if cost_tracker is not None:
                    # New session, possibly a new per-device program
                    # (elastic re-mesh keeps the GLOBAL batch shape, so
                    # the bucket key alone would dedup a now-stale cost)
                    cost_tracker.reset()
                if healer is not None:
                    if healer.devices is not None:
                        # Re-acquired backend, possibly smaller: re-cut
                        # the mesh (model axis kept, data axis re-derived
                        # — global batch invariant under the default
                        # shrink mode, so the loader and the schedule
                        # carry straight across) and re-derive everything
                        # device-facing against it.
                        from mx_rcnn_tpu.parallel.partition import (
                            elastic_mesh_spec)

                        if healer.outcome is not None:
                            # graftquorum: adopt the AGREED topology —
                            # every surviving host rebuilds in lockstep
                            # on the spec the heal round sealed (derived
                            # from the MINIMUM re-acquired capacity
                            # across the quorum), not its own local view.
                            respec = healer.outcome.spec
                        else:
                            respec = elastic_mesh_spec(
                                d0, m0, len(healer.devices),
                                cfg.train.batch_images * n_data,
                                mode=cfg.resilience.elastic_mode)
                        mesh = create_mesh(respec, devices=healer.devices)
                        model = build_model(cfg, mesh=mesh)
                        logger.info(
                            "graftheal: session rebuilt on mesh %s "
                            "(%d device(s))", dict(zip(
                                mesh.axis_names,
                                (int(s) for s in mesh.devices.shape))),
                            int(mesh.devices.size))
                        if (cfg.resilience.elastic_mode == "rescale"
                                and (cfg.train.batch_images * n_data)
                                % mesh.shape["data"]):
                            # RESCALE (elastic phase 2): the agreed data
                            # axis cannot carry the nominal global batch
                            # (not a divisor) — too-deep shrink or odd
                            # grow. Keep rows-per-device constant and let
                            # the GLOBAL batch scale with the fleet:
                            # rebuild the loader for the new shard count,
                            # re-derive the progress units, and rebase
                            # the carry position + schedule counters
                            # through the invariant (images consumed).
                            new_data = mesh.shape["data"]
                            old_ipd_live = ipd
                            if hasattr(loader, "close"):
                                loader.close()
                            n_local = local_data_shards(mesh)
                            loader = _build_loader(n_local)
                            steps_per_epoch = max(len(loader), 1)
                            ipd = (cfg.train.batch_images * accum
                                   * new_data * multi)
                            disp_per_epoch = max(1,
                                                 steps_per_epoch // multi)
                            images_done = carry.dispatch * old_ipd_live
                            carry.dispatch = images_done // ipd
                            if carry.opt_state is not None:
                                carry.opt_state = rebase_schedule_count(
                                    carry.opt_state,
                                    carry.epoch * steps_per_epoch
                                    + carry.dispatch * multi)
                            logger.warning(
                                "elastic rescale: global batch now %d "
                                "image(s)/dispatch (was %d); LR schedule "
                                "rebased to step %d — the batch-size "
                                "change makes bit-exactness with the "
                                "nominal run impossible by construction",
                                ipd, old_ipd_live,
                                carry.epoch * steps_per_epoch
                                + carry.dispatch * multi)
                    healer.note_devices(int(mesh.devices.size))

                # Optimizer/state from the carry: a restored opt_state
                # brings optax's schedule counter; a fresh one offsets
                # the schedule by begin_step instead (never both).
                b_epoch, b_skip = carry.epoch, carry.dispatch
                sched_begin = (0 if carry.opt_state is not None
                               else b_epoch * steps_per_epoch
                               + b_skip * multi)
                tx = build_optimizer(cfg, carry.params, steps_per_epoch,
                                     begin_step=sched_begin)
                state = create_train_state(carry.params, tx)
                if carry.opt_state is not None:
                    state = state.replace(opt_state=carry.opt_state)
                if b_epoch or b_skip:
                    state = state.replace(
                        step=jax.numpy.asarray(
                            b_epoch * steps_per_epoch + b_skip * multi,
                            jax.numpy.int32))

                # Partition specs are RE-DERIVED against the session's
                # mesh — after an elastic shrink the same rules bind to
                # the new model/data axes (parallel/partition.py).
                param_specs = None
                if cfg.network.tensor_parallel:
                    if ("model" in mesh.axis_names
                            and mesh.shape["model"] > 1):
                        from mx_rcnn_tpu.parallel.partition import (
                            shard_train_state, tp_param_specs)

                        param_specs = tp_param_specs(state.params)
                        state = shard_train_state(state, mesh, param_specs)
                    else:
                        logger.warning(
                            "network.tensor_parallel ignored: mesh model "
                            "axis is 1 (build the mesh as '<data>x"
                            "<model>', e.g. --tpu-mesh 4x2)")

                # flatcore (train/flatcore.py): persistent flat parameter/
                # optimizer storage — the update becomes a handful of
                # fused kernels and the DP allreduce one psum per buffer.
                # TP/PP (sharded-leaf) runs route back to the per-leaf
                # path inside flat_mode_for. Checkpoints (and the heal
                # carry) stay in TREE form — tree_state below — so every
                # restore path is mode-agnostic and a healed session
                # simply RE-CUTS the buffers via the SegmentTable.
                if getattr(cfg.train, "flat_params", False):
                    from mx_rcnn_tpu.train import flatcore as _flatcore

                    if _flatcore.flat_mode_for(cfg, params=state.params,
                                               param_specs=param_specs):
                        flat_core = _flatcore.FlatCore(
                            cfg, state.params, steps_per_epoch,
                            begin_step=sched_begin)
                        if carry.opt_state is not None:
                            state = flat_core.flatten_state(state)
                        else:
                            # Fresh slots: build the flat state directly —
                            # flatten_state would device_get every zero
                            # leaf of the per-leaf opt_state just to
                            # re-upload it as flat zeros.
                            state = flat_core.init_state(
                                state.params).replace(
                                step=jax.numpy.asarray(state.step,
                                                       jax.numpy.int32))
                        logger.info(
                            "flatcore: %d leaves -> %d flat buffer(s) %s",
                            len(flat_core.table.segments),
                            len(flat_core.table.sizes),
                            {d: n for d, n
                             in flat_core.table.sizes.items()})

                # Donation on the CPU backend is OFF — for every storage
                # mode, not just flat. Two observed corruption families:
                # (1) PR 5's flat crash — donating the ~100 MB flat
                # buffers races the CPU client's async execution (the
                # donated input of an enqueued step is reclaimed/
                # munmapped while referenced; segfault wanders over
                # later allocs); (2) the graftheal/resume shape — a
                # session rebuilt from HOST numpy trees (checkpoint
                # restore, heal carry) feeds numpy-backed arrays into a
                # donating step, and CPU zero-copy + donation writes
                # into/frees memory numpy owns (observed in the heal
                # shrink gate as 1e18 losses one dispatch after the
                # heal, or a segfault). Donation is an HBM-footprint
                # optimization — on the host-memory backend correctness
                # wins. TPU keeps it.
                donate = jax.default_backend() != "cpu"
                step_fn = make_train_step(model, cfg, mesh=mesh,
                                          donate=donate,
                                          forward_fn=(forward_fn
                                                      or forward_train),
                                          param_specs=param_specs,
                                          flat_core=flat_core,
                                          health=health_on)
                # Per-dispatch rng keys are derived from the dispatch's
                # GLOBAL index (fold_in), not a run-position-dependent
                # split chain — so a resumed/healed run consumes exactly
                # the keys the uninterrupted run would have (the
                # kill→resume bit-exactness gate), at O(1) resume cost.
                rng = jax.random.PRNGKey(seed + 1)

                for epoch in range(b_epoch, end_epoch):
                    if hasattr(loader, "set_epoch"):
                        # epoch order = f(seed, epoch): a resumed epoch
                        # replays exactly the order the uninterrupted run
                        # saw.
                        loader.set_epoch(epoch)
                    skip = b_skip if epoch == b_epoch else 0
                    batches = _dispatch_batches(loader, multi)
                    if skip:
                        logger.info(
                            "mid-epoch resume: skipping %d already-"
                            "trained dispatch(es) of epoch %d", skip,
                            epoch)
                        batches = itertools.islice(batches, skip, None)
                    bag = MetricBag()
                    if skip and carry.bag is not None \
                            and epoch == carry.epoch:
                        # Healed mid-epoch: keep accounting for the
                        # pre-loss dispatches so the epoch log/event
                        # covers the whole epoch, not just the remainder.
                        bag.restore(carry.bag)
                    pos = (epoch, skip)
                    # start=skip keeps i the TRUE epoch-local dispatch
                    # index on a mid-epoch resume — telemetry/log batch
                    # numbers continue where the interrupted run stopped
                    # rather than restarting at 0 over indices it already
                    # recorded.
                    for i, batch in timer.iterate(epoch, batches,
                                                  start=skip):
                        if chaos_spec.active:
                            # chaos site "train_dispatch": the injected
                            # device loss (device_lost_at_step) fires
                            # before the dispatch that would complete
                            # optimizer step K.
                            chaos_spec.fire(
                                "train_dispatch",
                                step=(epoch * steps_per_epoch
                                      + (i + 1) * multi))
                        k = jax.random.fold_in(  # graftlint: disable=prng-key-reuse — the root is folded with a DISTINCT global dispatch index each iteration (the resumable-key derivation; see the rng comment above)
                            rng, epoch * disp_per_epoch + i)
                        sharded = shard_batch(batch, mesh,
                                              stacked=multi > 1)
                        if cost_tracker is not None:
                            # One AOT cost capture per compiled shape
                            # bucket (dict lookup otherwise) — the
                            # `cost` event behind per-bucket MFU.
                            cost_tracker.observe(step_fn, state, sharded,
                                                 k)
                        if tracer is not None:
                            # Pre-dispatch arming: the window must
                            # INCLUDE step trace_at_step (even step 1).
                            tracer.before_step(timer.total_steps + 1)
                        if health_on:
                            state, metrics, pulse = step_fn(state, sharded,
                                                            k)
                        else:
                            state, metrics = step_fn(state, sharded, k)
                        pos = (epoch, i + 1)
                        timer.dispatched()
                        bag.update(metrics)
                        speedometer(epoch, i, bag)
                        if tracer is not None:
                            # timer.total_steps increments when the
                            # generator resumes — this dispatch is the
                            # (+1)th completed.
                            tracer.step_completed(timer.total_steps + 1)
                        if monitor is not None:
                            # stores a reference per dispatch; pulls to
                            # host (and runs the tripwires) only at the
                            # obs.health_every cadence. A tripped wire
                            # raises NumericsAnomaly out of the loop
                            # AFTER saving the known-good checkpoint.
                            monitor.observe(pulse, epoch=epoch,
                                            dispatch=i + 1)
                        done = i + 1  # dispatches complete in this epoch
                        if healer is not None:
                            healer.note_progress()
                            if healer.snapshot_due():
                                healer.set_fallback(_capture())
                        if chaos_spec.active:
                            chaos_spec.maybe_sigterm(
                                epoch * steps_per_epoch + done * multi)
                        if stopper is not None:
                            # Coordinated preemption (graftquorum): the
                            # signaled host PROPOSES its next boundary;
                            # every host folds in its own floor and ALL
                            # of them drain to the agreed max before the
                            # one barrier+publish in _honor_preemption.
                            # The un-signaled steady state costs one
                            # store read per dispatch.
                            gdone = epoch * disp_per_epoch + done
                            if (guard is not None and guard.requested
                                    and not stop_requested):
                                stopper.request(gdone)
                                stop_requested = True
                            agreed = stopper.check(gdone)
                            if agreed is not None and gdone >= agreed:
                                _honor_preemption(epoch, done)
                        elif guard is not None and guard.requested:
                            _honor_preemption(epoch, done)
                    # pos stays at (epoch, <last dispatch>) until the
                    # epoch-end work below completes: a heal landing
                    # inside this window then REPLAYS the whole block
                    # (the islice skips every dispatch, the bag restores
                    # from the carry) — the epoch event, the boundary
                    # save (a re-save is atomic and idempotent) and the
                    # epoch_callback all run instead of being silently
                    # dropped. Epoch callbacks should tolerate a rare
                    # re-invocation for the same epoch.
                    logger.info("Epoch[%d] done. %s", epoch, bag.format())
                    if obs_log.enabled:
                        # bag.format() above already drained the pending
                        # device scalars — this get() re-reads host-side
                        # sums only. Pad-waste accounting rides along:
                        # cumulative real/canvas pixels from the loader
                        # (graftprof; the canvas-packing baseline).
                        pad = (loader.pad_waste_stats()
                               if hasattr(loader, "pad_waste_stats")
                               else None)
                        obs_log.emit("epoch", epoch=epoch,
                                     metrics=bag.get(),
                                     **({"pad_waste": pad["pad_waste"],
                                         "pad_real_px": pad["real_px"],
                                         "pad_canvas_px": pad["canvas_px"]}
                                        if pad else {}))
                    # checkpoint_period > 1 (long small-epoch runs, e.g.
                    # the DETR gate's 150 epochs): save every Nth epoch
                    # and always the last — resume granularity traded
                    # against orbax save time.
                    # Explicit loader shutdown at epoch end: the epoch
                    # generator's finally already STOPPED the prefetcher
                    # when the loop drained it; close() additionally
                    # joins the worker threads so none outlive the epoch
                    # (data/loader.py).
                    if hasattr(loader, "close"):
                        loader.close()
                    boundary = (epoch + 1) * disp_per_epoch
                    if stopper is not None:
                        # Stop check BEFORE the epoch barrier: a host
                        # already waiting in the barrier cannot publish
                        # its drain floor, so a stop requested by a
                        # mid-epoch peer would idle the fleet until the
                        # deadline. (The residual race — a request
                        # landing between this check and the barrier —
                        # stays bounded by quorum_timeout_s.)
                        if (guard is not None and guard.requested
                                and not stop_requested):
                            stopper.request(boundary)
                            stop_requested = True
                        agreed = stopper.check(boundary)
                        if agreed is not None and boundary >= agreed:
                            _honor_preemption(epoch + 1, None)
                    if quorum is not None:
                        # Epoch-boundary saves get the same publication
                        # discipline as emergency saves: every host has
                        # finished the epoch before the leader publishes
                        # (the unbarriered-publish lint rule's contract).
                        quorum.barrier(f"epoch/{epoch + 1}")
                    epoch_saved = False
                    if is_primary() and (
                            (epoch + 1) % max(1, checkpoint_period) == 0
                            or epoch + 1 == end_epoch):
                        if flat_core is not None:
                            # on-disk form is ALWAYS the tree form —
                            # checkpoints stay interchangeable between
                            # flat and tree modes
                            save_params, save_opt = flat_core.tree_state(
                                state)
                        else:
                            save_params, save_opt = (state.params,
                                                     state.opt_state)
                        save = (writer.save if writer is not None
                                else save_checkpoint)
                        save(prefix, epoch + 1, save_params, save_opt,
                             means=cfg.train.bbox_means,
                             stds=cfg.train.bbox_stds,
                             num_classes=cfg.dataset.num_classes,
                             meta=_ckpt_meta(epoch + 1, None))
                        epoch_saved = True
                        if obs_log.enabled:
                            obs_log.emit("checkpoint", epoch=epoch + 1,
                                         prefix=prefix,
                                         durable=writer is None)
                    if epoch_callback:
                        epoch_callback(epoch, state, bag)
                    if stopper is not None:
                        # Re-check after the save/callback window — a
                        # signal that landed during epoch-end work, or a
                        # peer's request that arrived after the check
                        # above.
                        if (guard is not None and guard.requested
                                and not stop_requested):
                            stopper.request(boundary)
                            stop_requested = True
                        agreed = stopper.check(boundary)
                        if agreed is not None and boundary >= agreed:
                            _honor_preemption(epoch + 1, None,
                                              need_save=not epoch_saved)
                    elif guard is not None and guard.requested:
                        # Signal landed during epoch-end work: exit at
                        # the boundary. The save just enqueued (if any)
                        # goes durable in the finally below (writer.close
                        # publishes it); otherwise (checkpoint_period
                        # skipped this epoch) write a boundary checkpoint
                        # now so nothing is lost.
                        _honor_preemption(epoch + 1, None,
                                          need_save=not epoch_saved)
                    pos = (epoch + 1, 0)
                break  # trained through end_epoch — leave the session loop
            except RuntimeError as exc:
                # Step-time device/backend loss: heal in-process when the
                # PR 5 taxonomy says transient (and the consecutive-heal
                # cap has headroom); anything else propagates untouched.
                if healer is None or not healer.healable(exc):
                    raise
                try:
                    carry = healer.recover(exc, _capture)
                except QuorumExcludedError as qexc:
                    # The quorum sealed a heal round WITHOUT this host
                    # (it missed the rendezvous deadline): its session
                    # state is stale relative to the agreed topology.
                    # Exit resumably (rc 75, no local save — the fleet's
                    # checkpoints are authoritative) so the supervisor
                    # rejoins it via --resume auto.
                    if obs_log.enabled:
                        obs_log.emit("quorum", kind="excluded",
                                     error=str(qexc)[:300])
                    logger.warning("graftquorum: %s — exiting rc %d for "
                                   "rejoin via --resume auto", qexc,
                                   PreemptionExit().code)
                    raise PreemptionExit(None) from qexc
    except BaseException as exc:  # graftlint: disable=broad-except — crash telemetry, re-raised below
        if obs_log.enabled and not isinstance(exc, PreemptionExit):
            import traceback

            obs_log.emit("crash", error=repr(exc),
                         traceback=traceback.format_exc())
            if recorder is not None:
                # the rc!=0 artifact: the last-K events (incl. any
                # health readings) around the death, flushed even when
                # the JSONL buffer was not
                recorder.dump("crash")
        raise
    finally:
        if guard is not None:
            guard.uninstall()
        if watchdog is not None:
            watchdog.stop()
        if tracer is not None:
            tracer.close()  # an open stall window must land on disk
        if obs_log.enabled and cfg.obs.track_compiles:
            compile_track.deactivate()
        obs_log.close()
        if writer is not None:
            writer.close()  # the last save must be durable before return
        if hasattr(loader, "close"):
            loader.close()  # crash paths must not leak worker threads
    # Host-OWNED copies, not views: on the CPU backend device_get can
    # return zero-copy numpy views of runtime buffers, and callers hold
    # the returned tree across later jax work in the same process (the
    # kill->resume parity gate compares trees from THREE runs) — a
    # reused buffer would silently corrupt the caller's copy. One
    # end-of-training copy is noise next to an epoch.
    return jax.tree_util.tree_map(np.array, jax.device_get(state.params))
