"""Training core.

Reference layer L8 (SURVEY.md §2): rcnn/core/module.py MutableModule,
rcnn/core/metric.py (6 metrics), rcnn/core/callback.py (Speedometer,
do_checkpoint). Here: an optax optimizer with reference hyperparameters, a
pjit-able train step, host-side metric accumulators, and orbax checkpoints.
"""

from mx_rcnn_tpu.train.optimizer import build_optimizer, trainable_mask
from mx_rcnn_tpu.train.step import TrainState, create_train_state, make_train_step
from mx_rcnn_tpu.train.flatcore import FlatCore, FlatTrainState
from mx_rcnn_tpu.train.metrics import MetricBag
from mx_rcnn_tpu.train.callback import Speedometer

__all__ = [
    "build_optimizer",
    "trainable_mask",
    "TrainState",
    "create_train_state",
    "make_train_step",
    "FlatCore",
    "FlatTrainState",
    "MetricBag",
    "Speedometer",
]
