"""Training core.

Reference layer L8 (SURVEY.md §2): rcnn/core/module.py MutableModule,
rcnn/core/metric.py (6 metrics), rcnn/core/callback.py (Speedometer,
do_checkpoint). Here: an optax optimizer with reference hyperparameters, a
pjit-able train step, host-side metric accumulators, orbax checkpoints,
and the graftcast dtype policy (precision.py).

Attribute access is lazy (PEP 562): ``train/precision.py`` must be
importable from model code (models/*.py read the compute-dtype policy),
and an eager ``from .step import ...`` here would close the cycle
models → train → step → models at import time.
"""

from __future__ import annotations

import importlib

_EXPORTS = {
    "build_optimizer": "mx_rcnn_tpu.train.optimizer",
    "trainable_mask": "mx_rcnn_tpu.train.optimizer",
    "TrainState": "mx_rcnn_tpu.train.step",
    "create_train_state": "mx_rcnn_tpu.train.step",
    "make_train_step": "mx_rcnn_tpu.train.step",
    "FlatCore": "mx_rcnn_tpu.train.flatcore",
    "FlatTrainState": "mx_rcnn_tpu.train.flatcore",
    "MetricBag": "mx_rcnn_tpu.train.metrics",
    "Speedometer": "mx_rcnn_tpu.train.callback",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(module), name)
