"""Speedometer — the reference's only perf instrumentation, kept log-compatible.

Reference: rcnn/core/callback.py::Speedometer(batch_size, frequent) logging
'Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s' — the samples/sec line is
the throughput number BASELINE.md tracks, so the format is preserved.
"""

from __future__ import annotations

import time

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.train.metrics import MetricBag


class Speedometer:
    def __init__(self, batch_size: int, frequent: int = 20):
        self.batch_size = batch_size
        self.frequent = frequent
        self._tic = time.time()
        self._count = 0

    def __call__(self, epoch: int, batch: int, metrics: MetricBag):
        self._count += 1
        if self._count % self.frequent == 0:
            speed = self.frequent * self.batch_size / (time.time() - self._tic)
            logger.info(
                "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s",
                epoch, batch, speed, metrics.format(),
            )
            self._tic = time.time()
            return speed
        return None
