"""Speedometer — the reference's only perf instrumentation, kept log-compatible.

Reference: rcnn/core/callback.py::Speedometer(batch_size, frequent) logging
'Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s' — the samples/sec line is
the throughput number BASELINE.md tracks, so the format is preserved.
"""

from __future__ import annotations

import time

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.train.metrics import MetricBag


class Speedometer:
    """Logs the reference-format throughput line and, when a graftscope
    event log is attached, also emits each window as a ``step`` event
    carrying ``samples_per_sec`` (obs/report.py prefers these measured
    windows: they bracket the MetricBag drain, so they are honest
    end-to-end throughput)."""

    def __init__(self, batch_size: int, frequent: int = 20, event_log=None):
        self.batch_size = batch_size
        self.frequent = frequent
        self.event_log = event_log
        # monotonic, not wall: an NTP step inside a window would corrupt
        # the samples/sec line (wall-time-duration lint rule).
        self._tic = time.monotonic()
        self._count = 0

    def __call__(self, epoch: int, batch: int, metrics: MetricBag):
        self._count += 1
        if self._count % self.frequent == 0:
            speed = (self.frequent * self.batch_size
                     / (time.monotonic() - self._tic))
            logger.info(
                "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s",
                epoch, batch, speed, metrics.format(),
            )
            if self.event_log is not None and self.event_log.enabled:
                self.event_log.emit("step", epoch=epoch, batch=batch,
                                    samples_per_sec=round(speed, 3),
                                    window=self.frequent)
            self._tic = time.monotonic()
            return speed
        return None
