"""Checkpointing — orbax, with the reference's bbox_pred (un)normalization contract.

Reference: rcnn/core/callback.py::do_checkpoint saves per-epoch
``prefix-%04d.params`` after multiplying the bbox_pred weights by the target
stds (+ means into the bias) so saved checkpoints predict RAW deltas;
train_end2end.py's ``--resume`` and test-time load_param RE-normalize.

This build's contract (the SURVEY.md §6 'document the choice' option):
in-memory parameters ALWAYS predict normalized deltas; checkpoints on disk
ALWAYS store the raw-delta (un-normalized) form, exactly like the reference's
.params files. `save_checkpoint` folds stds/means in; `load_checkpoint` folds
them back out. Test-time decode multiplies by stds explicitly
(models/faster_rcnn.py::forward_test), so an on-disk checkpoint loaded for
inference via load_checkpoint round-trips to identical predictions.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from mx_rcnn_tpu.logger import logger


def _map_bbox_pred(params, fn_kernel, fn_bias):
    """Apply fns to the bbox_pred Dense leaves, leave everything else."""

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if "bbox_pred" in path:
            if path[-1] == "kernel":
                return fn_kernel(tree)
            if path[-1] == "bias":
                return fn_bias(tree)
        return tree

    return walk(params)


def unnormalize_bbox_params(params, means: Sequence[float], stds: Sequence[float],
                            num_classes: int):
    """Fold stds/means INTO bbox_pred so it predicts raw deltas (save form)."""
    stds_t = np.tile(np.asarray(stds, np.float32), num_classes)
    means_t = np.tile(np.asarray(means, np.float32), num_classes)
    return _map_bbox_pred(
        params,
        lambda k: k * stds_t[None, :],
        lambda b: b * stds_t + means_t,
    )


def renormalize_bbox_params(params, means: Sequence[float], stds: Sequence[float],
                            num_classes: int):
    """Inverse of unnormalize_bbox_params (load form)."""
    stds_t = np.tile(np.asarray(stds, np.float32), num_classes)
    means_t = np.tile(np.asarray(means, np.float32), num_classes)
    return _map_bbox_pred(
        params,
        lambda k: k / stds_t[None, :],
        lambda b: (b - means_t) / stds_t,
    )


def _prepare_save(prefix, epoch, params, opt_state, means, stds, num_classes):
    """The ONE encoding of the on-disk form (shared by sync and async
    paths): host (numpy) arrays — so checkpoints restore on any device
    topology, TP/PP-sharded or not — with bbox_pred folded to raw deltas."""
    path = os.path.abspath(os.path.join(prefix, f"{epoch:04d}"))
    to_save = {"params": jax.device_get(params)}
    if num_classes is not None:
        to_save["params"] = unnormalize_bbox_params(
            to_save["params"], means, stds, num_classes)
    if opt_state is not None:
        to_save["opt_state"] = jax.device_get(opt_state)
    return path, to_save


def save_checkpoint(prefix: str, epoch: int, params, opt_state=None, *,
                    means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
                    num_classes: Optional[int] = None):
    """Save epoch checkpoint at <prefix>/<epoch>/ (raw-delta form).

    opt_state is saved alongside when given (the reference cannot resume
    optimizer momentum — we can; --resume uses it when present).
    """
    path, to_save = _prepare_save(prefix, epoch, params, opt_state,
                                  means, stds, num_classes)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, to_save, force=True)
    logger.info("Saved checkpoint to %s", path)
    return path


class CheckpointWriter:
    """Async epoch checkpointing (orbax AsyncCheckpointer).

    The reference blocks training while `do_checkpoint` writes `.params`;
    here the epoch-end save is enqueued and the train loop keeps stepping
    — the array snapshot is taken up front (device→host copy inside
    orbax), the disk write runs in a background thread, and the PREVIOUS
    save is awaited before the next one starts (and at close()).

    Single-process use only: the primary-only save pattern of the
    multi-host path cannot satisfy orbax's cross-process commit barrier,
    so fit_detector falls back to the synchronous `save_checkpoint` when
    `jax.process_count() > 1`.
    """

    def __init__(self):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, prefix: str, epoch: int, params, opt_state=None, *,
             means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
             num_classes: Optional[int] = None):
        """Non-blocking analog of `save_checkpoint` — _prepare_save gives
        the identical on-disk form (host numpy; restores on any device
        topology); only the write is backgrounded. NOT durable on return:
        readers of the checkpoint (e.g. an eval driver watching the
        prefix) see it after the NEXT save or close()."""
        self._ckptr.wait_until_finished()
        path, to_save = _prepare_save(prefix, epoch, params, opt_state,
                                      means, stds, num_classes)
        self._ckptr.save(path, to_save, force=True)
        logger.info("Saving checkpoint to %s (async)", path)
        return path

    def close(self):
        """Release the background machinery (waits for the in-flight
        save first — orbax close() is wait + teardown)."""
        self._ckptr.close()


def load_checkpoint(prefix: str, epoch: int, *, template=None,
                    opt_state_template=None,
                    means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
                    num_classes: Optional[int] = None):
    """Load epoch checkpoint; returns (params, opt_state_or_None).

    Re-normalizes bbox_pred (reference: load_param + re-normalization under
    --resume in train_end2end.py). opt_state_template is REQUIRED to get a
    usable opt_state back: orbax restores untyped pytrees (dicts/lists), and
    optax states are namedtuples — restore against tx.init(params) or the
    result is train-step poison.
    """
    path = os.path.abspath(os.path.join(prefix, f"{epoch:04d}"))
    ckptr = ocp.PyTreeCheckpointer()
    item = None
    if template is not None:
        item = {"params": template["params"] if "params" in template
                else template}
        if opt_state_template is not None and _has_opt_state(path):
            item["opt_state"] = opt_state_template

    def _params_only(item):
        # Restore params while SKIPPING an on-disk opt_state (inference
        # load, or an opt_state from an older optimizer layout): orbax
        # rejects the structure mismatch of a plain restore, so this must
        # go through partial_restore. That kwarg needs orbax >= 0.5.21;
        # older versions raise TypeError — fall back to an untyped full
        # restore (flax params are plain dicts, so dropping the template
        # only loses dtype coercion).
        item = {"params": item["params"]}
        try:
            return ckptr.restore(
                path, args=ocp.args.PyTreeRestore(item=item,
                                                  partial_restore=True))
        except TypeError:
            return {"params": ckptr.restore(path)["params"]}

    if item is not None and "opt_state" not in item and _has_opt_state(path):
        restored = _params_only(item)
    else:
        try:
            restored = ckptr.restore(path, item=item)
        except (ValueError, KeyError, TypeError) as exc:
            # orbax signals template/layout mismatches with these; OSError
            # (missing/corrupt checkpoint) must propagate — resuming from
            # scratch because the disk is unreadable is the silent-failure
            # mode this narrowing exists to prevent.
            if item is not None and "opt_state" in item:
                # Saved opt_state from an older optimizer layout — restore
                # params only; the caller rebuilds the schedule via
                # begin_step.
                logger.warning(
                    "opt_state in %s does not match the current optimizer "
                    "layout (%s); restoring params only", path, exc)
                restored = _params_only(item)
            else:
                raise
    params = restored["params"]
    if num_classes is not None:
        params = renormalize_bbox_params(params, means, stds, num_classes)
    opt_state = restored.get("opt_state")
    if opt_state is not None and opt_state_template is None:
        opt_state = None  # untyped restore is unusable — see docstring
    return params, opt_state


def _has_opt_state(path: str) -> bool:
    try:
        meta = ocp.PyTreeCheckpointer().metadata(path)
        # orbax >= 0.5: StepMetadata with .item_metadata mapping; older
        # versions return the tree mapping directly.
        tree = getattr(meta, "item_metadata", meta)
        return "opt_state" in tree
    except (OSError, ValueError, KeyError, TypeError,
            AttributeError) as exc:
        # metadata API drift / unreadable metadata file — fall back to the
        # directory layout, but say so: a checkpoint whose metadata cannot
        # be read is worth a look before it bites at restore time.
        logger.warning("could not read checkpoint metadata at %s (%s); "
                       "probing directory layout instead", path, exc)
        return os.path.isdir(os.path.join(path, "opt_state"))


def latest_epoch(prefix: str) -> Optional[int]:
    """Highest saved epoch under prefix, or None — restart-from-latest support
    (failure recovery; the reference has none, SURVEY.md §6)."""
    if not os.path.isdir(prefix):
        return None
    epochs = [int(d) for d in os.listdir(prefix) if d.isdigit()]
    return max(epochs) if epochs else None
