"""Checkpointing — orbax, with the reference's bbox_pred (un)normalization contract.

Reference: rcnn/core/callback.py::do_checkpoint saves per-epoch
``prefix-%04d.params`` after multiplying the bbox_pred weights by the target
stds (+ means into the bias) so saved checkpoints predict RAW deltas;
train_end2end.py's ``--resume`` and test-time load_param RE-normalize.

This build's contract (the SURVEY.md §6 'document the choice' option):
in-memory parameters ALWAYS predict normalized deltas; checkpoints on disk
ALWAYS store the raw-delta (un-normalized) form, exactly like the reference's
.params files. `save_checkpoint` folds stds/means in; `load_checkpoint` folds
them back out. Test-time decode multiplies by stds explicitly
(models/faster_rcnn.py::forward_test), so an on-disk checkpoint loaded for
inference via load_checkpoint round-trips to identical predictions.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.resilience import chaos

#: Checkpoint directory names. Epoch-boundary saves keep the reference's
#: zero-padded epoch number ("0007" = 7 epochs complete); graftguard
#: emergency saves are dispatch-tagged ("0003d00012" = mid-epoch 3, 12
#: dispatches complete — resilience/preempt.py). Anything else under the
#: prefix (in-flight "*.tmp-*" dirs, orbax droppings) is never a resume
#: candidate.
_CKPT_NAME_RE = re.compile(r"^(\d+)(?:d(\d+))?$")


def checkpoint_name(epoch: int, dispatch: Optional[int] = None) -> str:
    if dispatch is None:
        return f"{epoch:04d}"
    return f"{epoch:04d}d{dispatch:05d}"


#: Topology sidecar inside a checkpoint dir (written into the tmp dir, so
#: it publishes atomically with the arrays). The graftheal axis of the
#: tree-form contract: records how big a dispatch WAS (global images per
#: dispatch, device count, mesh) so a resume onto a different topology
#: can recompute the dispatch skip against ITS global batch instead of
#: trusting a tag minted under another mesh. Orbax ignores the extra
#: file; checkpoints without one (pre-graftheal) restore as before.
META_NAME = "graft_meta.json"


def _write_meta(ckpt_dir: str, meta: Dict[str, Any]):
    with open(os.path.join(ckpt_dir, META_NAME), "w",
              encoding="utf-8") as fh:
        json.dump(meta, fh, indent=2, sort_keys=True)
        fh.write("\n")


def checkpoint_meta(prefix: str, epoch: int,
                    dispatch: Optional[int] = None) -> Optional[Dict]:
    """The topology sidecar of one checkpoint, or None (pre-graftheal
    checkpoints / unreadable sidecar — resume then keeps the legacy
    same-topology assumption, and says so)."""
    path = os.path.join(os.path.abspath(prefix),
                        checkpoint_name(epoch, dispatch), META_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as exc:
        logger.warning("unreadable checkpoint meta at %s (%s); resume "
                       "will assume the saving topology", path, exc)
        return None


def _map_bbox_pred(params, fn_kernel, fn_bias):
    """Apply fns to the bbox_pred Dense leaves, leave everything else."""

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if "bbox_pred" in path:
            if path[-1] == "kernel":
                return fn_kernel(tree)
            if path[-1] == "bias":
                return fn_bias(tree)
        return tree

    return walk(params)


def unnormalize_bbox_params(params, means: Sequence[float], stds: Sequence[float],
                            num_classes: int):
    """Fold stds/means INTO bbox_pred so it predicts raw deltas (save form)."""
    stds_t = np.tile(np.asarray(stds, np.float32), num_classes)
    means_t = np.tile(np.asarray(means, np.float32), num_classes)
    return _map_bbox_pred(
        params,
        lambda k: k * stds_t[None, :],
        lambda b: b * stds_t + means_t,
    )


def renormalize_bbox_params(params, means: Sequence[float], stds: Sequence[float],
                            num_classes: int):
    """Inverse of unnormalize_bbox_params (load form)."""
    stds_t = np.tile(np.asarray(stds, np.float32), num_classes)
    means_t = np.tile(np.asarray(means, np.float32), num_classes)
    return _map_bbox_pred(
        params,
        lambda k: k / stds_t[None, :],
        lambda b: (b - means_t) / stds_t,
    )


def _prepare_save(prefix, epoch, params, opt_state, means, stds, num_classes,
                  dispatch=None):
    """The ONE encoding of the on-disk form (shared by sync and async
    paths): host (numpy) arrays — so checkpoints restore on any device
    topology, TP/PP-sharded or not — with bbox_pred folded to raw deltas."""
    path = os.path.abspath(os.path.join(prefix,
                                        checkpoint_name(epoch, dispatch)))
    to_save = {"params": jax.device_get(params)}
    if num_classes is not None:
        to_save["params"] = unnormalize_bbox_params(
            to_save["params"], means, stds, num_classes)
    if opt_state is not None:
        to_save["opt_state"] = jax.device_get(opt_state)
    return path, to_save


def _finalize(tmp: str, final: str):
    """Atomically publish a fully-written checkpoint dir: a SIGKILL any
    time before the rename leaves only a ``*.tmp-*`` dir, which no resume
    path ever considers (latest_epoch/latest_checkpoint match the final
    name grammar only) — a truncated checkpoint can never be resumed
    from. The rename is same-directory, so same-filesystem.

    A re-save of an existing dir (force=True semantics) must not destroy
    the previous good checkpoint before the new one is published: the old
    dir is set ASIDE by rename (``<final>.old`` — outside the resume name
    grammar, deleted only after the new dir is in place), so the
    no-checkpoint window is two renames, not an rmtree. A kill between
    them leaves ``.old`` as a manually recoverable copy."""
    # chaos site "checkpoint_finalize": the crash-window test SIGKILLs
    # here — after the full write, before publication (test_resilience).
    chaos.site("checkpoint_finalize")
    old = final + ".old"
    if os.path.isdir(final):
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.replace(final, old)
        # chaos site "checkpoint_swap": previous checkpoint set aside,
        # new one not yet published — the narrowest crash window.
        chaos.site("checkpoint_swap")
    os.replace(tmp, final)
    if os.path.isdir(old):  # ours, or an orphan of a crashed predecessor
        shutil.rmtree(old)


def _tmp_path(final: str) -> str:
    return f"{final}.tmp-{os.getpid()}"


_TMP_SUFFIX_RE = re.compile(r"\.tmp-(\d+)$")


def _sweep_stale_tmps(prefix: str):
    """Remove ``*.tmp-<pid>`` dirs abandoned by DEAD processes — every
    kill inside the save window (the scenario graftguard engineers for)
    leaves one at full model size, and no other path deletes them.
    Live pids are skipped (checkpointing is single-writer per prefix,
    but don't yank an in-flight write on a stale assumption); crashed
    ``.old`` asides are kept — they are the recovery copy."""
    if not os.path.isdir(prefix):
        return
    for name in os.listdir(prefix):
        m = _TMP_SUFFIX_RE.search(name)
        if not m or int(m.group(1)) == os.getpid():
            continue
        try:
            os.kill(int(m.group(1)), 0)
        except ProcessLookupError:
            logger.warning("removing stale checkpoint tmp %s (dead pid)",
                           name)
            shutil.rmtree(os.path.join(prefix, name), ignore_errors=True)
        except PermissionError:
            pass  # pid exists (not ours): in-flight, leave it


def save_checkpoint(prefix: str, epoch: int, params, opt_state=None, *,
                    means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
                    num_classes: Optional[int] = None,
                    dispatch: Optional[int] = None,
                    meta: Optional[Dict[str, Any]] = None):
    """Save epoch checkpoint at <prefix>/<epoch>/ (raw-delta form).

    opt_state is saved alongside when given (the reference cannot resume
    optimizer momentum — we can; --resume uses it when present).
    ``dispatch`` tags a graftguard mid-epoch emergency save (see
    checkpoint_name); the write lands in a ``*.tmp-*`` dir and is
    published by one atomic rename, so a kill mid-save leaves no
    resumable-looking partial state. ``meta`` (a small JSON-able dict —
    the graftheal topology sidecar, see META_NAME) is written into the
    tmp dir so it publishes atomically with the arrays.
    """
    path, to_save = _prepare_save(prefix, epoch, params, opt_state,
                                  means, stds, num_classes, dispatch)
    _sweep_stale_tmps(prefix)
    ckptr = ocp.PyTreeCheckpointer()
    tmp = _tmp_path(path)
    ckptr.save(tmp, to_save, force=True)
    if meta is not None:
        _write_meta(tmp, meta)
    _finalize(tmp, path)
    logger.info("Saved checkpoint to %s", path)
    return path


class CheckpointWriter:
    """Async epoch checkpointing (orbax AsyncCheckpointer).

    The reference blocks training while `do_checkpoint` writes `.params`;
    here the epoch-end save is enqueued and the train loop keeps stepping
    — the array snapshot is taken up front (device→host copy inside
    orbax), the disk write runs in a background thread, and the PREVIOUS
    save is awaited before the next one starts (and at close()).

    Single-process use only: the primary-only save pattern of the
    multi-host path cannot satisfy orbax's cross-process commit barrier,
    so fit_detector falls back to the synchronous `save_checkpoint` when
    the coordination world size is > 1 — LOUDLY: the fallback emits one
    ``checkpoint`` event with ``fallback="sync"`` so a fleet run that
    silently lost async saving shows it in the event stream (unit-gated
    in tests/test_resilience.py).
    """

    def __init__(self):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        # (tmp, final, meta) of the in-flight save; published (renamed)
        # only after orbax confirms the write finished — the same atomic
        # crash-window guarantee as the sync path, deferred. The meta
        # sidecar is written just before the rename (the background
        # writer owns the tmp dir until then).
        self._pending: Optional[Tuple[str, str, Optional[Dict]]] = None

    def _publish_pending(self):
        if self._pending is not None:
            tmp, final, meta = self._pending
            self._pending = None
            if meta is not None:
                _write_meta(tmp, meta)
            _finalize(tmp, final)
            logger.info("Checkpoint %s durable", final)

    def save(self, prefix: str, epoch: int, params, opt_state=None, *,
             means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
             num_classes: Optional[int] = None,
             dispatch: Optional[int] = None,
             meta: Optional[Dict[str, Any]] = None):
        """Non-blocking analog of `save_checkpoint` — _prepare_save gives
        the identical on-disk form (host numpy; restores on any device
        topology); only the write is backgrounded. NOT durable on return:
        readers of the checkpoint (e.g. an eval driver watching the
        prefix) see it after the NEXT save or close() — the final dir
        name only appears at that point (the write itself targets a
        ``*.tmp-*`` dir, so a kill mid-write leaves nothing resumable)."""
        self._ckptr.wait_until_finished()
        self._publish_pending()
        path, to_save = _prepare_save(prefix, epoch, params, opt_state,
                                      means, stds, num_classes, dispatch)
        _sweep_stale_tmps(prefix)
        tmp = _tmp_path(path)
        self._ckptr.save(tmp, to_save, force=True)
        self._pending = (tmp, path, meta)
        logger.info("Saving checkpoint to %s (async)", path)
        return path

    def close(self):
        """Release the background machinery (waits for the in-flight
        save first — orbax close() is wait + teardown — then publishes
        it)."""
        self._ckptr.close()
        self._publish_pending()


def load_checkpoint(prefix: str, epoch: int, *, template=None,
                    opt_state_template=None,
                    means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
                    num_classes: Optional[int] = None,
                    dispatch: Optional[int] = None):
    """Load epoch checkpoint; returns (params, opt_state_or_None).

    Re-normalizes bbox_pred (reference: load_param + re-normalization under
    --resume in train_end2end.py). opt_state_template is REQUIRED to get a
    usable opt_state back: orbax restores untyped pytrees (dicts/lists), and
    optax states are namedtuples — restore against tx.init(params) or the
    result is train-step poison. ``dispatch`` selects a graftguard
    mid-epoch emergency save (checkpoint_name).
    """
    path = os.path.abspath(os.path.join(prefix,
                                        checkpoint_name(epoch, dispatch)))
    ckptr = ocp.PyTreeCheckpointer()
    item = None
    if template is not None:
        item = {"params": template["params"] if "params" in template
                else template}
        if opt_state_template is not None and _has_opt_state(path):
            item["opt_state"] = opt_state_template

    def _params_only(item):
        # Restore params while SKIPPING an on-disk opt_state (inference
        # load, or an opt_state from an older optimizer layout): orbax
        # rejects the structure mismatch of a plain restore, so this must
        # go through partial_restore. That kwarg needs orbax >= 0.5.21;
        # older versions raise TypeError — fall back to an untyped full
        # restore (flax params are plain dicts, so dropping the template
        # only loses dtype coercion).
        item = {"params": item["params"]}
        try:
            return ckptr.restore(
                path, args=ocp.args.PyTreeRestore(item=item,
                                                  partial_restore=True))
        except TypeError:
            return {"params": ckptr.restore(path)["params"]}

    if item is not None and "opt_state" not in item and _has_opt_state(path):
        restored = _params_only(item)
    else:
        try:
            restored = ckptr.restore(path, item=item)
        except (ValueError, KeyError, TypeError) as exc:
            # orbax signals template/layout mismatches with these; OSError
            # (missing/corrupt checkpoint) must propagate — resuming from
            # scratch because the disk is unreadable is the silent-failure
            # mode this narrowing exists to prevent.
            if item is not None and "opt_state" in item:
                # Saved opt_state from an older optimizer layout — restore
                # params only; the caller rebuilds the schedule via
                # begin_step.
                logger.warning(
                    "opt_state in %s does not match the current optimizer "
                    "layout (%s); restoring params only", path, exc)
                restored = _params_only(item)
            else:
                raise
    params = restored["params"]
    if num_classes is not None:
        params = renormalize_bbox_params(params, means, stds, num_classes)
    opt_state = restored.get("opt_state")
    if opt_state is not None and opt_state_template is None:
        opt_state = None  # untyped restore is unusable — see docstring
    return params, opt_state


def _has_opt_state(path: str) -> bool:
    try:
        meta = ocp.PyTreeCheckpointer().metadata(path)
        # orbax >= 0.5: StepMetadata with .item_metadata mapping; older
        # versions return the tree mapping directly.
        tree = getattr(meta, "item_metadata", meta)
        return "opt_state" in tree
    except (OSError, ValueError, KeyError, TypeError,
            AttributeError) as exc:
        # metadata API drift / unreadable metadata file — fall back to the
        # directory layout, but say so: a checkpoint whose metadata cannot
        # be read is worth a look before it bites at restore time.
        logger.warning("could not read checkpoint metadata at %s (%s); "
                       "probing directory layout instead", path, exc)
        return os.path.isdir(os.path.join(path, "opt_state"))


def latest_epoch(prefix: str) -> Optional[int]:
    """Highest saved EPOCH-BOUNDARY checkpoint under prefix, or None —
    restart-from-latest support (failure recovery; the reference has
    none, SURVEY.md §6). Ignores graftguard emergency (dispatch-tagged)
    saves and in-flight ``*.tmp-*`` dirs; ``--resume auto`` goes through
    latest_checkpoint to pick those up."""
    if not os.path.isdir(prefix):
        return None
    epochs = [int(d) for d in os.listdir(prefix) if d.isdigit()]
    return max(epochs) if epochs else None


def latest_checkpoint(prefix: str) -> Optional[Tuple[int, Optional[int]]]:
    """The most-advanced resume point under prefix: ``(epoch, None)`` for
    an epoch-boundary checkpoint ("epoch" epochs complete) or
    ``(epoch, dispatch)`` for an emergency save (mid-epoch ``epoch``,
    ``dispatch`` dispatches complete — graftguard preemption or a
    graftheal capture). Progress orders as the tuple: epoch save N ≡
    (N, 0) sits between (N-1, d) emergencies and any (N, d>0) emergency.
    An emergency save carrying the SAME progress as a boundary save
    ("0003d00000" vs "0003" — a capture at dispatch 0) ties: the
    emergency save wins DETERMINISTICALLY (it is the later artifact and
    may carry a topology sidecar the boundary save predates), and the
    choice is logged — never left to directory-listing order. Unfinished
    ``*.tmp-*`` writes never match the name grammar, so a kill mid-save
    can never be resumed from.

    graftquorum: a multi-host emergency save whose ``graft_meta.json``
    records FEWER participating hosts than the quorum expected (a host
    died between the barrier and the commit — a torn fleet save) is
    SKIPPED with a warning instead of winning the tie-break; resume then
    falls back to the next-most-advanced complete checkpoint."""
    if not os.path.isdir(prefix):
        return None
    candidates = []
    names = set()
    for d in os.listdir(prefix):
        m = _CKPT_NAME_RE.match(d)
        if not m:
            continue
        names.add(d)
        epoch, dispatch = int(m.group(1)), m.group(2)
        # third element: emergency (dispatch-tagged) outranks an
        # epoch-boundary save at equal progress — the deterministic
        # tie-break (strict ordering, never directory-listing order).
        key = (epoch, int(dispatch) if dispatch is not None else 0,
               1 if dispatch is not None else 0)
        candidates.append((key, d))
    for key, best_name in sorted(candidates, reverse=True):
        epoch, dispatch, emergency = key
        if emergency:
            meta = checkpoint_meta(prefix, epoch, dispatch) or {}
            hosts, expected = meta.get("hosts"), meta.get("host_count")
            if (hosts is not None and expected is not None
                    and len(hosts) < int(expected)):
                logger.warning(
                    "skipping torn multi-host emergency save %s/%s: its "
                    "%s records %d of %d participating host(s) — a host "
                    "died mid-commit; resuming from the next complete "
                    "checkpoint", prefix, best_name, META_NAME,
                    len(hosts), int(expected))
                continue
        if emergency and dispatch == 0 and checkpoint_name(epoch) in names:
            logger.info(
                "resume tie at epoch %d: emergency save %s and boundary "
                "save %s carry the same progress — picking the emergency "
                "save (deterministic tie-break)", epoch, best_name,
                checkpoint_name(epoch))
        return epoch, (dispatch if emergency else None)
    return None
