"""flatcore: persistent flat parameter/optimizer-state storage.

The r4 roofline (PERF.md item 3) left ONE formulation-invariant non-conv
cost in the train step: the optimizer update's ~6 ms floor, immune to five
different implementations because it is a serialization cost of launching
hundreds of per-leaf kernels (params → grads → momentum/moments, one tiny
kernel per leaf per transform), not HBM bandwidth. This module removes the
many-buffer shape itself:

- All trainable leaves live in ONE contiguous dtype-segregated buffer per
  tree (params / trace / Adam mu+nu), described by a precomputed STATIC
  segment table (path, shape, dtype, offset) built from the model's
  canonical flatten spec (models/zoo.py::param_flatten_spec).
- The param tree the forward sees is materialized INSIDE the compiled step
  as zero-copy views — static `buf[off:off+size].reshape(shape)` slices
  that XLA fuses into their consumers. Gradients are taken with respect to
  the BUFFER, so the backward accumulates straight into one flat gradient
  per dtype — no step-time ravel/unravel (optax.flatten's measured 10.2 ms
  failure mode: ~300 slice ops each way, every step).
- The update (train/optimizer.py::flat_sgd_update / flat_adamw_update) is
  a handful of fused elementwise kernels over the flat buffers; under a
  data mesh the gradient allreduce is ONE psum per buffer instead of one
  per leaf (the Horovod-fusion / ZeRO-flat-state shape, ready for the
  v5e-16 DP north star).
- Freezing is a precomputed per-segment 0/1 scale buffer carried in the
  state (NOT a baked-in constant — a params-sized literal would bloat the
  executable), preserving the r3 hard-zero fix: frozen elements update by
  exactly 0.0 and bit-retain their values.

graftcast (train/precision.py): under ``train.compute_dtype=bf16`` the
f32 buffers above are MASTER weights, and the state additionally carries
a bf16 COMPUTE SHADOW per float buffer (``FlatTrainState.compute``):
the update writes the masters in f32 (bit-exact vs the f32 policy given
equal grads) and re-materializes the shadow with ONE ``convert`` per
dtype buffer — a program output, so XLA cannot fold it away or
re-duplicate it into consumer fusions (``optimization_barrier`` is
dropped by the CPU pipeline and has no AD rule on jax 0.4.x). The
forward's param views slice the shadow — except the f32 islands (norm
statistics/affine, ``precision.is_island_param``), which stay views of
the master — and the loss differentiates w.r.t. the (master, shadow)
pair, so the backward yields one bf16 cotangent per buffer that is cast
UP once and summed into the f32 master gradient before the DP psum and
the optimizer update. Same values as flax's per-leaf promotion (cast
commutes with slicing); the per-leaf cast tree is simply gone.

Mode routing: `train.flat_params` opts in; TP/PP trees keep the per-leaf
path (parallel/partition.py::flat_segment_specs — a sharded leaf has no
contiguous image inside a replicated flat buffer).

Checkpoint contract: the on-disk form is ALWAYS the tree form —
`FlatCore.tree_state` reconstructs the exact optax opt_state structure
(slot layout discovered positionally from `jax.eval_shape(tx.init)`), so
checkpoints are bit-for-bit interchangeable between modes and with every
earlier round's checkpoints (tests/test_flatcore.py round-trips both
directions, sync and async).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.train import precision
from mx_rcnn_tpu.train.optimizer import (
    build_optimizer,
    effective_fixed_patterns,
    flat_adamw_update,
    flat_sgd_update,
    lr_schedule,
    trainable_mask,
)


@dataclass(frozen=True)
class Segment:
    """One leaf's image inside its dtype buffer. Static metadata only."""

    path: str
    dtype: str  # buffer key (param dtype name)
    offset: int
    size: int
    shape: Tuple[int, ...]
    trainable: bool


class SegmentTable:
    """Static (path, shape, dtype, offset) table for one param tree.

    Built once per (model, cfg) from the canonical flatten spec; segments
    within a dtype buffer follow the spec's order, so offsets are a pure
    function of the tree structure — two processes (or two rounds) with
    the same model agree on every offset without communicating.
    """

    def __init__(self, params, mask_tree):
        from mx_rcnn_tpu.models.zoo import param_flatten_spec

        spec = param_flatten_spec(params)
        self.treedef = jax.tree_util.tree_structure(params)
        mask_leaves = [bool(m) for m in jax.tree_util.tree_leaves(mask_tree)]
        if len(mask_leaves) != len(spec):
            raise ValueError(
                f"trainable mask has {len(mask_leaves)} leaves for a "
                f"{len(spec)}-leaf param tree")
        segments = []
        offsets: Dict[str, int] = {}
        for (path, shape, dtype), trainable in zip(spec, mask_leaves):
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            off = offsets.get(dtype, 0)
            segments.append(Segment(path, dtype, off, size, shape, trainable))
            offsets[dtype] = off + size
        self.segments: Tuple[Segment, ...] = tuple(segments)
        self.sizes: Dict[str, int] = dict(offsets)

    def flatten(self, tree) -> Dict[str, np.ndarray]:
        """Tree → {dtype: flat buffer}. Host-side (state creation and
        checkpoint conversion); the hot path never calls it — gradients
        are produced flat by construction."""
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(self.segments):
            raise ValueError(
                f"tree has {len(leaves)} leaves, table has "
                f"{len(self.segments)} segments")
        groups: Dict[str, list] = {d: [] for d in self.sizes}
        for seg, leaf in zip(self.segments, leaves):
            arr = np.asarray(jax.device_get(leaf))
            if arr.shape != seg.shape:
                raise ValueError(
                    f"leaf {seg.path}: shape {arr.shape} != table "
                    f"{seg.shape}")
            groups[seg.dtype].append(arr.reshape(-1).astype(seg.dtype))
        return {d: (np.concatenate(parts) if parts
                    else np.zeros((0,), d))
                for d, parts in groups.items()}

    def unflatten(self, bufs) -> Any:
        """{dtype: buffer} → param tree of static slice/reshape views.
        Trace-safe: under jit each leaf is a zero-copy view XLA fuses into
        its consumer; on host (numpy buffers) it is plain slicing."""
        leaves = [bufs[s.dtype][s.offset:s.offset + s.size].reshape(s.shape)
                  for s in self.segments]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unflatten_mixed(self, master, compute,
                        use_compute: Tuple[bool, ...]) -> Any:
        """Two-source view assembly (graftcast): segment ``i`` slices the
        COMPUTE shadow when ``use_compute[i]`` (conv/dense weights — the
        bf16 fast path) and the MASTER buffer otherwise (f32 islands:
        norm statistics/affine, plus any non-float dtype group). Same
        static slice/reshape views as ``unflatten`` — only the source
        buffer differs per segment."""
        if len(use_compute) != len(self.segments):
            raise ValueError(
                f"use_compute has {len(use_compute)} flags for "
                f"{len(self.segments)} segments")
        leaves = [
            (compute if uc else master)[s.dtype]
            [s.offset:s.offset + s.size].reshape(s.shape)
            for s, uc in zip(self.segments, use_compute)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def mask_buffers(self) -> Dict[str, np.ndarray]:
        """Per-dtype 0/1 trainability scale, materialized host-side once
        (it rides in the state so it is program INPUT, not a params-sized
        compile-time literal)."""
        out = {}
        for d, total in self.sizes.items():
            vals = np.concatenate([
                np.full(s.size, 1.0 if s.trainable else 0.0, d)
                for s in self.segments if s.dtype == d]) if total else \
                np.zeros((0,), d)
            out[d] = vals
        return out

    def segment_view(self, bufs, path: str):
        """Named lookup — THE way host code reads one leaf out of a flat
        buffer (the flat-state-access lint rule points here)."""
        for s in self.segments:
            if s.path == path:
                return bufs[s.dtype][s.offset:s.offset + s.size].reshape(
                    s.shape)
        raise KeyError(path)


@dataclass(frozen=True)
class _SlotSpec:
    """One optimizer slot (trace / mu / nu): which template-leaf positions
    it owns and its per-param-dtype accumulator dtype."""

    indices: Tuple[int, ...]
    dtypes: Tuple[Tuple[str, str], ...]  # ((param-dtype, slot-dtype), ...)

    def dtype_map(self) -> Dict[str, str]:
        return dict(self.dtypes)


class FlatTrainState(struct.PyTreeNode):
    """TrainState twin for flat mode: one buffer per dtype per tree.

    `masks` is carried (and returned unchanged) rather than closed over so
    donation aliases it instead of embedding a params-sized constant.
    `count` mirrors optax's schedule/Adam step count — it can differ from
    `step` on --begin_epoch restarts whose schedule is offset by
    begin_step instead (see fit_detector's resume logic).

    graftcast: `compute` is the compute-dtype shadow of every FLOAT
    master buffer ({master-dtype-name: bf16 buffer} — the key stays the
    GROUP name), refreshed by `apply` with one cast per buffer; `{}`
    under the f32 policy (no extra leaves, no behavior change). Being
    state, it is a program OUTPUT — the one reliable way to pin the cast
    as a single materialized kernel — and donation recycles it like any
    other buffer.
    """

    step: jnp.ndarray
    count: jnp.ndarray
    flat: Any                       # {dtype: f32 master params buffer}
    slots: Any                      # tuple of {dtype: slot buffer}
    masks: Any                      # {dtype: 0/1 buffer}
    compute: Any                    # {dtype: compute shadow} | {} (f32)
    core: "FlatCore" = struct.field(pytree_node=False)

    def apply_gradients(self, grad_bufs) -> "FlatTrainState":
        return self.core.apply(self, grad_bufs)

    @property
    def params(self):
        """Host-owned param tree — np COPIES, never zero-copy views of the
        donated device buffers (the FlatCore.tree_state use-after-free
        hazard). This is the read-only surface epoch callbacks and the
        fit_detector return path share with tree-mode TrainState; traced
        code reads the flat buffers directly and never calls it."""
        return self.core.table.unflatten(
            {d: np.array(jax.device_get(b))
             for d, b in self.flat.items()})


class FlatCore:
    """Per-(cfg, model) flat-storage engine: segment table + slot layout +
    the fused update. Static — closed over by the jitted step exactly like
    optax's tx (hashed by identity)."""

    def __init__(self, cfg: Config, params, steps_per_epoch: int = 1000,
                 begin_step: int = 0):
        self.kind = cfg.train.optimizer
        # The tree-mode twin: provides the opt_state structure template for
        # checkpoint interchange AND stays the authority on masking/
        # schedule semantics (build_optimizer validates cfg).
        self.tx = build_optimizer(cfg, params, steps_per_epoch, begin_step)
        self.sched = lr_schedule(cfg, steps_per_epoch, begin_step)
        self.clip = float(cfg.train.clip_gradient)
        self.wd = float(cfg.train.wd)
        self.momentum = float(cfg.train.momentum)
        mask_tree = trainable_mask(params, effective_fixed_patterns(cfg))
        self.table = SegmentTable(params, mask_tree)
        self._discover_slots(params)
        # graftcast policy (train/precision.py): which segments read the
        # compute shadow vs the f32 master. Islands (norm statistics and
        # affine — precision.is_island_param) and non-float groups stay
        # master views; everything else takes the one-cast bf16 path.
        self.policy = precision.policy_of(cfg)
        self.use_compute: Tuple[bool, ...] = tuple(
            self.policy.mixed
            and jnp.issubdtype(jnp.dtype(s.dtype), jnp.floating)
            and jnp.dtype(s.dtype) != self.policy.compute_jnp
            and not precision.is_island_param(s.path)
            for s in self.table.segments)

    # -- slot layout -------------------------------------------------------

    def _discover_slots(self, params):
        """Positional slot discovery from the optax state template.

        `tx.init` flattens to: zero or more scalar int32 counts, plus m
        contiguous groups of k array leaves, where k = number of trainable
        segments and each group matches their shapes in order (frozen
        leaves are optax.MaskedNode — no leaves). sgd → 1 group (trace);
        adamw → 2 (mu, nu). Anything else is an optimizer layout this
        module does not know how to flatten — fail loudly.
        """
        template = jax.eval_shape(self.tx.init, params)
        leaves, self.opt_treedef = jax.tree_util.tree_flatten(template)
        self._tmpl_n = len(leaves)
        train_segs = [s for s in self.table.segments if s.trainable]
        self.train_segments = tuple(train_segs)
        count_pos, array_pos = [], []
        for i, leaf in enumerate(leaves):
            if (getattr(leaf, "ndim", None) == 0
                    and jnp.issubdtype(leaf.dtype, jnp.integer)):
                count_pos.append(i)
            else:
                array_pos.append(i)
        k = len(train_segs)
        if k == 0 or len(array_pos) % k:
            raise ValueError(
                f"cannot map optimizer state onto flat slots: "
                f"{len(array_pos)} array leaves over {k} trainable segments")
        slots = []
        for j in range(len(array_pos) // k):
            idxs = array_pos[j * k:(j + 1) * k]
            per_dtype: Dict[str, set] = {}
            for seg, i in zip(train_segs, idxs):
                leaf = leaves[i]
                if tuple(leaf.shape) != seg.shape:
                    raise ValueError(
                        f"slot {j} leaf {i} shape {tuple(leaf.shape)} does "
                        f"not match segment {seg.path} {seg.shape}")
                per_dtype.setdefault(
                    seg.dtype, set()).add(jnp.dtype(leaf.dtype).name)
            dtypes = []
            for d, names in sorted(per_dtype.items()):
                if len(names) != 1:
                    raise ValueError(
                        f"slot {j} mixes dtypes {sorted(names)} within the "
                        f"{d} param group")
                dtypes.append((d, names.pop()))
            slots.append(_SlotSpec(tuple(idxs), tuple(dtypes)))
        expected = {"sgd": 1, "adamw": 2}[self.kind]
        if len(slots) != expected:
            raise ValueError(
                f"{self.kind} template yielded {len(slots)} slots, "
                f"expected {expected}")
        self.slots: Tuple[_SlotSpec, ...] = tuple(slots)
        self.count_pos = tuple(count_pos)

    def _slot_buffers(self, spec: _SlotSpec,
                      fill=None) -> Dict[str, np.ndarray]:
        """Full-size per-dtype slot buffers (frozen regions stay zero);
        `fill` maps trainable segments to leaf arrays (None → zeros)."""
        out = {d: np.zeros(self.table.sizes[d], spec.dtype_map()[d])
               for d in self.table.sizes if d in spec.dtype_map()}
        # dtype groups with no trainable segments still need a buffer so
        # the update's dict zip stays total
        for d in self.table.sizes:
            out.setdefault(d, np.zeros(self.table.sizes[d], d))
        if fill:
            for seg, leaf in fill:
                arr = np.asarray(jax.device_get(leaf))
                out[seg.dtype][seg.offset:seg.offset + seg.size] = (
                    arr.reshape(-1))
        return out

    # -- state construction / conversion -----------------------------------

    def compute_shadow(self, flat) -> Dict[str, Any]:
        """The compute-dtype shadow of the float master buffers — ONE
        cast per buffer ({} under the f32 policy)."""
        if not self.policy.mixed:
            return {}
        return {d: buf for d, buf in precision.cast_buffers(
            flat, self.policy.compute_jnp).items()
            if buf.dtype != jnp.dtype(d)}

    def params_view(self, flat, compute):
        """The param tree a forward should see for (master, shadow)
        buffers: compute views for the fast path, master views for the
        f32 islands (and for everything under the f32 policy)."""
        if not self.policy.mixed:
            return self.table.unflatten(flat)
        return self.table.unflatten_mixed(flat, compute, self.use_compute)

    def master_grads(self, grads) -> Dict[str, Any]:
        """Backward output → f32 master-gradient buffers.

        Under the bf16 policy the loss is differentiated w.r.t. the
        (flat, compute) pair, so ``grads`` arrives as that pair: the
        master cotangent (island leaves, already f32) plus the shadow
        cotangent (bf16). The shadow grad is cast UP once per buffer —
        the transpose twin of ``compute_shadow``'s cast — and summed, so
        everything downstream (DP psum, optimizer update) is float32.
        Under f32 the buffers pass through untouched."""
        if not self.policy.mixed:
            return grads
        g_master, g_compute = grads
        out = dict(g_master)
        for d, g in g_compute.items():
            out[d] = out[d] + g.astype(jnp.dtype(d))
        return out

    def init_state(self, params) -> FlatTrainState:
        """Fresh flat state (the create_train_state analog)."""
        flat = {d: jnp.asarray(b)
                for d, b in self.table.flatten(params).items()}
        slots = tuple({d: jnp.asarray(b)
                       for d, b in self._slot_buffers(spec).items()}
                      for spec in self.slots)
        masks = {d: jnp.asarray(b)
                 for d, b in self.table.mask_buffers().items()}
        return FlatTrainState(
            step=jnp.zeros((), jnp.int32), count=jnp.zeros((), jnp.int32),
            flat=flat, slots=slots, masks=masks,
            compute=self.compute_shadow(flat), core=self)

    def flatten_state(self, state) -> FlatTrainState:
        """TrainState (tree mode, fresh or checkpoint-restored) → flat."""
        flat = {d: jnp.asarray(b)
                for d, b in self.table.flatten(state.params).items()}
        opt_leaves, treedef = jax.tree_util.tree_flatten(state.opt_state)
        if treedef != self.opt_treedef:
            raise ValueError(
                "opt_state structure does not match this FlatCore's "
                "optimizer template — rebuild the core from the same cfg")
        slots = []
        for spec in self.slots:
            fill = [(seg, opt_leaves[i])
                    for seg, i in zip(self.train_segments, spec.indices)]
            slots.append({d: jnp.asarray(b) for d, b in
                          self._slot_buffers(spec, fill).items()})
        count = (jnp.asarray(opt_leaves[self.count_pos[0]], jnp.int32)
                 if self.count_pos else jnp.asarray(state.step, jnp.int32))
        masks = {d: jnp.asarray(b)
                 for d, b in self.table.mask_buffers().items()}
        return FlatTrainState(
            step=jnp.asarray(state.step, jnp.int32), count=count,
            flat=flat, slots=tuple(slots), masks=masks,
            compute=self.compute_shadow(flat), core=self)

    def tree_state(self, fstate: FlatTrainState):
        """Flat state → (params tree, exact optax opt_state) — the
        checkpoint form. Inverse of flatten_state bit-for-bit: trainable
        slot elements round-trip; frozen regions are zeros on both sides
        (tree mode stores no slot at all for frozen leaves).

        The host buffers are OWNED COPIES (np.array), never zero-copy
        views of the device buffers: on the CPU backend `np.asarray(jax
        array)` aliases the XLA buffer, and the train step DONATES the
        flat state — an async checkpoint writer still reading an aliased
        view when the next step reuses that memory is a use-after-free
        (observed as heap corruption crashing at unrelated sites)."""
        params = self.table.unflatten(
            {d: np.array(jax.device_get(b))
             for d, b in fstate.flat.items()})
        leaves: list = [None] * self._tmpl_n
        count = np.int32(jax.device_get(fstate.count))
        for i in self.count_pos:
            leaves[i] = np.asarray(count)
        for spec, bufs in zip(self.slots, fstate.slots):
            host = {d: np.array(jax.device_get(b))
                    for d, b in bufs.items()}
            for seg, i in zip(self.train_segments, spec.indices):
                leaves[i] = (host[seg.dtype]
                             [seg.offset:seg.offset + seg.size]
                             .reshape(seg.shape))
        opt_state = jax.tree_util.tree_unflatten(self.opt_treedef, leaves)
        return params, opt_state

    # -- the update --------------------------------------------------------

    def apply(self, state: FlatTrainState, grads) -> FlatTrainState:
        """One optimizer step over flat buffers (trace-safe; the jitted
        step calls this through FlatTrainState.apply_gradients).

        ``grads``: f32 master-gradient buffers ({dtype: buffer}). Under
        the bf16 policy the backward yields a (master, shadow) cotangent
        pair — the CALLER combines it via ``master_grads`` before the
        DP psum / accumulation (train/step.py::_grads_of), so the update
        itself always runs on f32 buffers, bit-exact across policies
        given equal gradients. The compute shadow is re-materialized
        from the NEW masters at the end — the one cast per dtype buffer,
        pinned by being a program output."""
        lr = self.sched(state.count)
        # optax's safe_int32_increment, computed ONCE: AdamW's bias
        # correction and the stored schedule count share this value.
        bump = jnp.where(state.count < jnp.iinfo(jnp.int32).max,
                         state.count + 1, state.count).astype(jnp.int32)
        if self.kind == "sgd":
            new_flat, new_trace = flat_sgd_update(
                state.flat, grads, state.slots[0], state.masks,
                lr=lr, momentum=self.momentum, wd=self.wd,
                clip_delta=self.clip,
                trace_dtypes=self._full_dtype_map(self.slots[0]))
            new_slots = (new_trace,)
        else:
            new_flat, new_mu, new_nu = flat_adamw_update(
                state.flat, grads, state.slots[0], state.slots[1],
                state.masks, bump,
                lr=lr, wd=self.wd, max_norm=self.clip,
                mu_dtypes=self._full_dtype_map(self.slots[0]))
            new_slots = (new_mu, new_nu)
        return state.replace(step=state.step + 1, count=bump,
                             flat=new_flat, slots=new_slots,
                             compute=self.compute_shadow(new_flat))

    def _full_dtype_map(self, spec: _SlotSpec) -> Dict[str, str]:
        out = {d: d for d in self.table.sizes}  # identity for sloteless dts
        out.update(spec.dtype_map())
        return out


def flat_mode_for(cfg: Config, params=None, param_specs=None) -> bool:
    """Should this run take the flat path? TP/PP (and any explicitly
    sharded param tree) route back to per-leaf — the warning names why."""
    if not getattr(cfg.train, "flat_params", False):
        return False
    if cfg.network.tensor_parallel or cfg.network.pp_stages:
        logger.warning(
            "train.flat_params ignored: %s shards param leaves over the "
            "model axis — a sharded leaf has no contiguous image in a flat "
            "buffer; keeping the per-leaf update path",
            "tensor_parallel" if cfg.network.tensor_parallel else
            f"pp_stages={cfg.network.pp_stages}")
        return False
    if param_specs is not None:
        from mx_rcnn_tpu.parallel.partition import flat_segment_specs

        if params is None or flat_segment_specs(params, param_specs) is None:
            logger.warning(
                "train.flat_params ignored: param tree carries non-"
                "replicated shardings; keeping the per-leaf update path")
            return False
    return True
