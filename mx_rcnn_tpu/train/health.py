"""graftpulse in-graph numerics probes — the device half of health telemetry.

PR 11 made bf16 the default compute dtype, and nothing watched numerical
health: graftscope/graftprof say how FAST a run is, but a run that
overflows in bf16, diverges after a heal, or silently trains on NaNs was
invisible until the epoch metric. This module computes the health signal
INSIDE the compiled train step, flatcore-native:

- ``finite_stats`` is ONE fused pass over a buffer: nonfinite count plus
  the finite-masked squared sum (XLA fuses both reductions with the
  ``isfinite`` mask into a single sweep). Masking keeps the norm
  informative when a few elements have overflowed — "3 nonfinite, norm
  unchanged" localizes a blowup far better than an all-NaN norm.
- ``step_health`` probes the three tensors that tell the mixed-precision
  story (grads, params, the update delta). Flat mode probes each flat
  dtype buffer — one fused reduction per buffer, the flatcore shape;
  tree mode gets a coarser whole-tree fold (one count + one norm per
  kind), since per-leaf reductions would re-create exactly the
  many-small-kernels serialization flatcore removed.
- The result is a dict of SCALARS returned as extra step outputs
  (train/step.py ``health=True``): the cadenced device→host read
  (obs/health.py HealthMonitor, ``obs.health_every``) piggybacks on the
  step's existing output fetch — zero added host syncs per step and zero
  new compiled executables. With ``obs.health_every=0`` the step program
  is bit-identical to the pre-graftpulse one.

Key schema (the contract obs/health.py folds): ``{kind}/{group}/nf``
(nonfinite count, int32) and ``{kind}/{group}/sq`` (finite-masked squared
sum, f32) for kind ∈ {grad, param, update}, group = flat buffer dtype
name or the literal ``tree``; plus ``loss`` (the dispatch's pooled mean
total loss, f32).

This file is the sanctioned home of jit-reachable ``jnp.isfinite``-style
probe reductions — the ``health-host-pull`` lint rule flags them
anywhere else (route new probes through here instead).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

#: key suffixes (obs/health.py parses on these)
NF_SUFFIX = "/nf"
SQ_SUFFIX = "/sq"
#: pin-entry prefix — full BUFFERS riding the health dict purely to be
#: program outputs (see step_health's pin_grads); obs/health.py skips
#: them (never pulled to host), they are dropped with the dict.
PIN_PREFIX = "_pin/"


def finite_stats(x: jnp.ndarray):
    """One fused pass: ``(nonfinite count, finite-masked squared sum)``.
    The squared sum accumulates in f32 regardless of the buffer dtype
    (a bf16 square would overflow at ~2^64 where the f32 sum does not
    even notice)."""
    finite = jnp.isfinite(x)
    nf = jnp.asarray(x.size, jnp.int32) - jnp.sum(finite.astype(jnp.int32))
    xf = jnp.where(finite, x, 0).astype(jnp.float32)
    return nf, jnp.sum(xf * xf)


def probe_buffers(kind: str, bufs: Dict[str, Any]) -> Dict[str, Any]:
    """Flat mode: one fused reduction per float dtype buffer. Non-float
    groups carry no overflow information and are skipped."""
    out: Dict[str, Any] = {}
    for d, b in bufs.items():
        if not jnp.issubdtype(b.dtype, jnp.floating):
            continue
        nf, sq = finite_stats(b)
        out[f"{kind}/{d}{NF_SUFFIX}"] = nf
        out[f"{kind}/{d}{SQ_SUFFIX}"] = sq
    return out


def probe_tree(kind: str, tree: Any) -> Dict[str, Any]:
    """Tree mode: the coarse whole-tree fold — per-leaf stats summed into
    ONE (count, squared-sum) pair under the group name ``tree``."""
    nf_tot = jnp.zeros((), jnp.int32)
    sq_tot = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        nf, sq = finite_stats(leaf)
        nf_tot = nf_tot + nf
        sq_tot = sq_tot + sq
    return {f"{kind}/tree{NF_SUFFIX}": nf_tot,
            f"{kind}/tree{SQ_SUFFIX}": sq_tot}


def step_health(old_state, grads, new_state, flat_core, loss,
                pin_grads: bool = False) -> Dict[str, Any]:
    """The per-optimizer-step health dict (train/step.py calls this inside
    the traced step, after the update).

    ``grads`` are the FINAL gradients the update consumed: flat mode's
    f32 master-gradient buffers (post ``master_grads`` under bf16 — the
    shadow cotangent's nonfinites survive the cast up) or the tree-mode
    gradient tree. The update delta is probed as ``new − old`` per
    master buffer/leaf: a nonfinite delta with finite grads localizes
    the fault to the optimizer math rather than the backward.

    ``pin_grads`` (flat mode, CPU backend, train/step.py decides): ALSO
    return the probed gradient buffers under ``_pin/`` keys — never
    pulled to host (obs/health.py skips the prefix), they exist purely
    to make the buffers PROGRAM OUTPUTS. CPU XLA schedules the flat
    backward pathologically when its cotangent buffer has only
    scalar-reduction side-consumers (measured on the 64^2 tiny step:
    +3.4 s/step — ~8x — for the grad probes alone; +30 ms with the
    buffer pinned as an output), and ``optimization_barrier`` is
    dropped by that pipeline — output-ness is the one reliable pin,
    exactly the graftcast shadow lesson (PERF.md round 8). The probed
    param/update tensors need no pin: ``new_state`` already IS an
    output. On TPU the pin is off (an extra live grad-sized HBM buffer
    per step buys nothing there)."""
    out: Dict[str, Any] = {"loss": jnp.asarray(loss, jnp.float32)}
    if flat_core is not None:
        out.update(probe_buffers("grad", grads))
        out.update(probe_buffers("param", new_state.flat))
        delta = {d: new_state.flat[d] - old_state.flat[d]
                 for d in new_state.flat}
        out.update(probe_buffers("update", delta))
        if pin_grads:
            out.update({f"{PIN_PREFIX}{d}": g for d, g in grads.items()})
    else:
        out.update(probe_tree("grad", grads))
        out.update(probe_tree("param", new_state.params))
        delta = jax.tree_util.tree_map(
            lambda a, b: a - b, new_state.params, old_state.params)
        out.update(probe_tree("update", delta))
    return out


def fold_multi_step(h_seq: Dict[str, Any]) -> Dict[str, Any]:
    """Multi-step dispatch: the scan stacks K per-step health rows —
    fold to one dict per dispatch. Nonfinite counts SUM over the K steps
    (a poisoned middle step must surface even if the last one happens to
    look clean); norms and the loss take the LAST step's row (the
    trailing-window statistics track the newest state)."""
    return {k: (jnp.sum(v, axis=0) if k.endswith(NF_SUFFIX) else v[-1])
            for k, v in h_seq.items()}
