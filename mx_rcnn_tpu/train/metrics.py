"""Host-side running metric accumulators.

Reference: rcnn/core/metric.py — RPNAccMetric, RPNLogLossMetric,
RPNL1LossMetric, RCNNAccMetric, RCNNLogLossMetric, RCNNL1LossMetric, each an
mx.metric.EvalMetric reading fixed output-group slots and keeping
(sum, count) running averages printed by Speedometer.

Here the per-batch values are computed on device inside the train step
(train/step.py::_metrics_from_aux) and this bag just averages scalars —
no per-batch device→host sync of full tensors.
"""

from __future__ import annotations

from typing import Dict, Iterable

METRIC_NAMES = (
    "RPNAcc", "RPNLogLoss", "RPNL1Loss",
    "RCNNAcc", "RCNNLogLoss", "RCNNL1Loss",
    "TotalLoss",  # not one of the reference's 6 — kept for the epoch log
)


class MetricBag:
    """Accumulates per-batch metric dicts LAZILY: update() stores the device
    scalars without forcing a host sync; conversion happens in get() (at
    Speedometer log time), so the train loop never blocks on metrics."""

    def __init__(self, names: Iterable[str] = METRIC_NAMES):
        self.names = tuple(names)
        self.reset()

    def reset(self):
        self._pending = []
        self._sums = {n: 0.0 for n in self.names}
        self._counts = {n: 0 for n in self.names}

    def update(self, metrics: Dict):
        self._pending.append(metrics)

    def _drain(self):
        for m in self._pending:
            for n in self.names:
                if n in m:
                    self._sums[n] += float(m[n])
                    self._counts[n] += 1
        self._pending = []

    def snapshot(self):
        """Drained (sums, counts) as host floats — the graftheal carry:
        captured with the train state so a healed mid-epoch resume keeps
        accounting for the pre-loss dispatches (and a snapshot-rollback
        replay re-adds exactly the dispatches it replays)."""
        self._drain()
        return dict(self._sums), dict(self._counts)

    def restore(self, snap):
        """Inverse of snapshot() onto a fresh bag."""
        sums, counts = snap
        self._pending = []
        self._sums = {n: float(sums.get(n, 0.0)) for n in self.names}
        self._counts = {n: int(counts.get(n, 0)) for n in self.names}

    def get(self) -> Dict[str, float]:
        """Per-slot running means of the metrics ACTUALLY SEEN — each slot
        averages over the updates that carried it (the reference
        EvalMetrics' (sum_metric, num_inst) semantics), so a model family
        that doesn't emit a slot (DETR has no RPN) doesn't log zeros for
        it and an intermittent slot isn't diluted.

        Contract: slots never seen are OMITTED — including from an empty
        bag, which returns {} (one rule, no empty-epoch special case).
        Fixed-key consumers should use ``bag.get().get(name, default)``."""
        self._drain()
        return {n: self._sums[n] / c
                for n in self.names if (c := self._counts[n]) > 0}

    def format(self) -> str:
        return "\t".join(f"Train-{n}={v:.6f}" for n, v in self.get().items())
