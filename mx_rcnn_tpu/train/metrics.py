"""Host-side running metric accumulators.

Reference: rcnn/core/metric.py — RPNAccMetric, RPNLogLossMetric,
RPNL1LossMetric, RCNNAccMetric, RCNNLogLossMetric, RCNNL1LossMetric, each an
mx.metric.EvalMetric reading fixed output-group slots and keeping
(sum, count) running averages printed by Speedometer.

Here the per-batch values are computed on device inside the train step
(train/step.py::_metrics_from_aux) and this bag just averages scalars —
no per-batch device→host sync of full tensors.
"""

from __future__ import annotations

from typing import Dict, Iterable

METRIC_NAMES = (
    "RPNAcc", "RPNLogLoss", "RPNL1Loss",
    "RCNNAcc", "RCNNLogLoss", "RCNNL1Loss",
    "TotalLoss",  # not one of the reference's 6 — kept for the epoch log
)


class MetricBag:
    """Accumulates per-batch metric dicts LAZILY: update() stores the device
    scalars without forcing a host sync; conversion happens in get() (at
    Speedometer log time), so the train loop never blocks on metrics."""

    def __init__(self, names: Iterable[str] = METRIC_NAMES):
        self.names = tuple(names)
        self.reset()

    def reset(self):
        self._pending = []
        self._sums = {n: 0.0 for n in self.names}
        self._seen = set()
        self._count = 0

    def update(self, metrics: Dict):
        self._pending.append(metrics)

    def _drain(self):
        for m in self._pending:
            for n in self.names:
                if n in m:
                    self._sums[n] += float(m[n])
                    self._seen.add(n)
            self._count += 1
        self._pending = []

    def get(self) -> Dict[str, float]:
        """Running means of the metrics ACTUALLY SEEN — a model family
        that doesn't emit a slot (DETR has no RPN) doesn't log zeros
        for it. A bag that received no updates at all reports every slot
        as 0.0 (so fixed-key consumers never KeyError on an empty epoch).
        """
        self._drain()
        if not self._seen:
            return {n: 0.0 for n in self.names}
        c = max(self._count, 1)
        return {n: self._sums[n] / c for n in self.names if n in self._seen}

    def format(self) -> str:
        return "\t".join(f"Train-{n}={v:.6f}" for n, v in self.get().items())
