"""Optimizer: SGD + momentum with the reference's exact knobs.

Reference: the fit kwargs in train_end2end.py —
``optimizer='sgd', momentum 0.9, wd 5e-4, clip_gradient 5,
MultiFactorScheduler(lr_step), rescale_grad=1/batch_size`` — plus parameter
freezing via ``fixed_param_prefix`` handed to MutableModule.

Mapping:
- clip_gradient: MXNet clips ELEMENTWISE to [−c, c] → optax.clip.
- wd: MXNet SGD couples weight decay into the gradient → add_decayed_weights
  before the momentum step.
- rescale_grad 1/batch: our losses already normalize per local image and DP
  gradients are mean-reduced, so no extra rescale is needed (documented
  equivalence — see models/losses.py).
- MultiFactorScheduler: piecewise-constant LR dropped by ``lr_factor`` at
  ``lr_step`` epoch boundaries.
- freezing: a boolean mask — frozen leaves receive zero updates AND no weight
  decay (MXNet's fixed_param_names are simply absent from the executor's
  grad list).
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence

import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config


def trainable_mask(params, patterns: Sequence[str]):
    """True for trainable leaves; False where any pattern is a path substring.

    Frozen-BN params (gamma/beta/moving_*) are always frozen in this
    framework (reference: use_global_stats + fixed gamma/beta).
    """
    always_frozen = ("moving_mean", "moving_var")

    def decide(path) -> bool:
        keys = [getattr(p, "key", str(p)) for p in path]
        joined = "/".join(str(k) for k in keys)
        if any(f in joined for f in always_frozen):
            return False
        # BN affine anywhere: frozen (gamma/beta leaf names).
        leaf = keys[-1] if keys else ""
        if leaf in ("gamma", "beta"):
            return False
        return not any(pat in joined for pat in patterns)

    return jax.tree_util.tree_map_with_path(lambda p, _: decide(p), params)


def lr_schedule(cfg: Config, steps_per_epoch: int,
                begin_step: int = 0) -> optax.Schedule:
    """MultiFactorScheduler analog: lr × lr_factor at each lr_step epoch.

    begin_step offsets the schedule for restarts whose opt_state (and with
    it optax's internal step count) was not restored — e.g. --begin_epoch
    with only a params checkpoint. With a restored opt_state the count
    resumes by itself and begin_step must stay 0.
    """
    boundaries = {
        int(e * steps_per_epoch): cfg.train.lr_factor for e in cfg.train.lr_step
    }
    base = optax.piecewise_constant_schedule(cfg.train.lr, boundaries)
    if begin_step:
        return lambda step: base(step + begin_step)
    return base


def effective_fixed_patterns(cfg: Config) -> tuple:
    """The optimizer-mask patterns implied by the config as a whole.

    The ResNet stem/stage1 patterns exist to mirror the reference's
    fixed_param_prefix, whose forward-side twin is the freeze_at
    stop_gradient cut (models/backbones.py). With freeze_at=0 (the
    from-scratch profile) there is no cut and the stem is MEANT to train —
    keeping the patterns would freeze it at random init. One knob, one
    freeze."""
    pats = tuple(cfg.network.fixed_param_patterns)
    if cfg.network.freeze_at < 2:
        # the stage1 cut exists only from freeze_at=2 up
        pats = tuple(p for p in pats if p != "stage1")
    if cfg.network.freeze_at == 0:
        # ResNet stem AND the VGG conv1-2 prefix both unfreeze
        pats = tuple(p for p in pats
                     if p not in ("conv0", "bn0")
                     and not p.startswith(("conv1_", "conv2_")))
    return pats


def build_optimizer(cfg: Config, params, steps_per_epoch: int = 1000,
                    begin_step: int = 0):
    mask = trainable_mask(params, effective_fixed_patterns(cfg))
    sched = lr_schedule(cfg, steps_per_epoch, begin_step)
    # Optional bf16 storage for the momentum / first-moment slot: the
    # update is HBM-bound (PERF.md r4 — ~6-7.6 ms/step across families),
    # and this halves one full-size tree's traffic. f32 default.
    slot_dtype = (None if cfg.train.opt_state_dtype == "float32"
                  else cfg.train.opt_state_dtype)
    if cfg.train.optimizer == "adamw":
        # Transformer families (DETR/ViTDet): AdamW + global-norm clip,
        # per their papers. Weight decay is decoupled (inside adamw).
        inner = optax.chain(
            optax.clip_by_global_norm(cfg.train.clip_gradient),
            optax.adamw(learning_rate=sched, weight_decay=cfg.train.wd,
                        mu_dtype=slot_dtype),
        )
    elif cfg.train.optimizer == "sgd":
        inner = optax.chain(
            optax.clip(cfg.train.clip_gradient),
            optax.add_decayed_weights(cfg.train.wd),
            optax.sgd(learning_rate=sched, momentum=cfg.train.momentum,
                      accumulator_dtype=slot_dtype),
        )
    else:
        raise ValueError(
            f"train.optimizer must be 'sgd' or 'adamw', got "
            f"{cfg.train.optimizer!r}")
    # Freezing is a HARD ZERO on the update, not optax.masked: masked()
    # passes the RAW GRADIENT through for masked-out leaves (optax's
    # contract), which apply_updates would then ADD to the frozen params —
    # gradient ascent. Harmless only when the frozen grads are
    # structurally zero (the stop_gradient-cut C4 prefix), actively wrong
    # for the alternate-training frozen-trunk stages where grads through
    # `features` are real (caught by test_stages.py's trunk-sharing
    # assertion).
    # One code path for DP and TP. Alternatives were measured on-chip and
    # REJECTED (r4, PERF.md): optax.flatten (one big vector) costs 10.2 ms
    # vs this chain's 6.1 — the ravel/unravel are ~300 slice ops each
    # way; a hand-fused one-kernel-per-leaf SGD measures 6.46 ms — the
    # update is HBM-traffic-bound (~1.2 GB/step at f32), not
    # kernel-count-bound, so the chain is already at its floor.
    labels = jax.tree_util.tree_map(
        lambda t: "train" if t else "frozen", mask)
    return optax.multi_transform(
        {"train": inner, "frozen": optax.set_to_zero()}, labels)


def rebase_schedule_count(opt_state, step: int):
    """Rewrite every scalar integer count leaf of an optax state to
    ``step`` (host-side; returns a new tree).

    Elastic cross-topology resume (graftheal): a restored opt_state's
    schedule/Adam counters are in the SAVING run's optimizer-step units.
    Once the dispatch skip has been converted through the images-consumed
    invariant, this run counts steps in its OWN units (its
    steps_per_epoch, its LR schedule) — left unrebased, every schedule
    read (warmup/decay boundaries) would happen at the old run's
    position, silently bending the LR trajectory. Scalar integer leaves
    are exactly optax's counts (the same invariant flatcore's slot
    discovery keys on)."""
    import numpy as np

    def _fix(leaf):
        arr = np.asarray(leaf)
        if arr.ndim == 0 and np.issubdtype(arr.dtype, np.integer):
            return np.asarray(step, arr.dtype)
        return leaf

    return jax.tree_util.tree_map(_fix, opt_state)


# ---------------------------------------------------------------------------
# Flat update path (train/flatcore.py storage). The r4 probes showed the
# ~6 ms update floor is a serialization cost of launching hundreds of
# per-leaf kernels, not HBM bandwidth — so the structural fix is fewer,
# bigger buffers, not cheaper per-leaf math. These functions are the
# elementwise twins of the optax chains above, applied to flatcore's
# dtype-segregated buffers ({dtype-name: 1-D array}): a handful of fused
# kernels per step instead of one-per-leaf-per-transform. Freezing is a
# precomputed per-segment 0/1 scale (`masks`) multiplied into the gradient
# AND the weight-decay term — the same hard-zero semantics as the
# multi_transform above (the r3 frozen-grad fix): frozen elements see a
# structurally zero update, so `p + (-lr * 0)` leaves them bit-identical.
# ---------------------------------------------------------------------------


def flat_sgd_update(params: Mapping[str, jnp.ndarray],
                    grads: Mapping[str, jnp.ndarray],
                    trace: Mapping[str, jnp.ndarray],
                    masks: Mapping[str, jnp.ndarray], *,
                    lr, momentum: float, wd: float, clip_delta: float,
                    trace_dtypes: Mapping[str, str]):
    """clip → add_decayed_weights → trace → (−lr), fused over flat buffers.

    Expression-for-expression the optax chain in build_optimizer (clip of a
    hard-zeroed gradient is zero; the trace buffer covers frozen segments
    but stays exactly 0 there), so the trainable elements are BIT-identical
    to the tree path — same elementwise ops in the same order, just over
    one buffer per dtype. `trace_dtypes` mirrors optax.trace's
    accumulator_dtype (the opt_state_dtype memory lever): the update uses
    the uncast value; the stored slot is cast.
    """
    new_p: Dict[str, jnp.ndarray] = {}
    new_t: Dict[str, jnp.ndarray] = {}
    for d, p in params.items():
        m = masks[d]
        u = jnp.clip(grads[d] * m, -clip_delta, clip_delta)
        u = u + wd * (p * m)
        t_new = u + momentum * trace[d]
        step = jnp.asarray(-1.0, t_new.dtype) * jnp.asarray(
            lr, t_new.dtype) * t_new
        new_p[d] = jnp.asarray(p + step).astype(p.dtype)
        new_t[d] = t_new.astype(trace_dtypes[d])
    return new_p, new_t


def flat_adamw_update(params: Mapping[str, jnp.ndarray],
                      grads: Mapping[str, jnp.ndarray],
                      mu: Mapping[str, jnp.ndarray],
                      nu: Mapping[str, jnp.ndarray],
                      masks: Mapping[str, jnp.ndarray],
                      count_inc, *,
                      lr, wd: float, max_norm: float,
                      b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                      mu_dtypes: Mapping[str, str]):
    """clip_by_global_norm → scale_by_adam → +wd·p → (−lr), flat twin.

    The global norm reduces over the masked buffers (= the trainable
    leaves, exactly what the multi_transform 'train' partition feeds
    optax's clip) — per-BUFFER partial sums instead of per-leaf, so the
    reduction order differs by float rounding only. Everything after is
    elementwise. `count_inc` is the POST-increment optax step count
    (scale_by_adam's safe_int32_increment result) — FlatCore.apply
    computes the bump once and stores the same value, so the bias
    correction here and the schedule count can never desynchronize.
    """
    g = {d: grads[d] * masks[d] for d in grads}
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(v)) for v in g.values()))
    trigger = gn < max_norm
    bc1 = 1 - b1 ** count_inc
    bc2 = 1 - b2 ** count_inc
    new_p: Dict[str, jnp.ndarray] = {}
    new_mu: Dict[str, jnp.ndarray] = {}
    new_nu: Dict[str, jnp.ndarray] = {}
    for d, p in params.items():
        gc = jax.lax.select(trigger, g[d],
                            (g[d] / gn.astype(g[d].dtype)) * max_norm)
        mu_new = (1 - b1) * gc + b1 * mu[d]
        nu_new = (1 - b2) * (gc ** 2) + b2 * nu[d]
        mu_hat = mu_new / bc1.astype(mu_new.dtype)
        nu_hat = nu_new / bc2.astype(nu_new.dtype)
        u = mu_hat / (jnp.sqrt(nu_hat + 0.0) + eps)
        u = u + wd * (p * masks[d])
        u = jnp.asarray(-1.0, u.dtype) * jnp.asarray(lr, u.dtype) * u
        new_p[d] = jnp.asarray(p + u).astype(p.dtype)
        new_mu[d] = mu_new.astype(mu_dtypes[d])
        new_nu[d] = nu_new
    return new_p, new_mu, new_nu
