"""graftcast — the central mixed-precision policy (one knob, one cast).

Before this module the repo's bf16 story was implicit: every flax module
carried ``dtype=bfloat16`` (per PR 1) and cast ITS OWN float32 param
leaves down at every use — a per-leaf cast tree re-materialized inside
each compiled step, with no single place that said which numerics run in
which dtype. ``train.compute_dtype`` replaces that with an explicit
policy, and flatcore (train/flatcore.py) makes the policy structural:

- **f32 master weights.** Parameters are stored float32, always — in the
  flat master buffers (flat mode), the tree leaves (tree mode), and the
  checkpoint (tree form on disk, bit-for-bit interchangeable between
  ``f32`` and ``bf16`` runs in both directions).
- **bf16 compute.** With ``train.compute_dtype=bf16`` the forward and
  backward run bfloat16: activations and the conv/matmul weights are
  bf16, and matmuls/convs accumulate f32 via XLA's MXU default plus the
  explicit ``preferred_element_type`` sites (ops/ring_attention.py,
  ops/roi_align.py, ops/nms_pallas.py).
- **One cast per dtype buffer (flat mode).** FlatCore carries a COMPUTE
  SHADOW of each float master buffer in the train state
  (``FlatTrainState.compute``): the update writes the f32 masters and
  re-materializes the shadow with ONE ``convert`` per dtype buffer — a
  program output, so XLA cannot re-duplicate it into consumer fusions
  (``optimization_barrier`` is dropped by the CPU pipeline and has no AD
  rule on jax 0.4.x; an output is the only reliable pin). The param tree
  the forward sees is slice/reshape views of the shadow; the per-leaf
  cast tree is gone (gated in tests/test_precision.py).
- **f32 islands.** The numerics that f16-family dtypes demonstrably
  break stay float32 regardless of the knob: all norm statistics
  (``is_island_param`` keeps the frozen-BN/GroupNorm/LayerNorm
  parameters on f32 master views; flax's norm layers already compute
  their statistics in f32), the losses, ``bbox_transform``
  encode/decode, and NMS scores — model code routes those casts through
  :func:`island` (the ``dtype-cast-in-jit`` lint rule points here).
- **f32 gradients.** The backward's buffer cotangent is cast UP once per
  buffer (the transpose twin of the shadow cast), so the DP psum and the
  optimizer update run float32 — the update is bit-exact against the
  ``f32`` path given identical gradients (tests/test_precision.py).

Tree (per-leaf) mode under ``bf16`` keeps flax's per-leaf promotion —
same values (cast commutes with slicing), just without the structural
one-cast win; TP/PP runs therefore lose nothing.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

#: accepted ``train.compute_dtype`` spellings → canonical numpy-dtype name
_CANON = {
    "f32": "float32",
    "float32": "float32",
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
}

#: canonical short spelling (config docs, bench/ledger rows)
SHORT = {"float32": "f32", "bfloat16": "bf16"}

#: leaf names that ARE norm statistics / affine (FrozenBatchNorm) — plus
#: ``pos_embed`` (models/vit.py): it is bilinearly RESIZED before its
#: per-use cast, and cast does not commute with resize, so a bf16 shadow
#: view would diverge from tree mode's resize-f32-then-cast
_ISLAND_LEAVES = frozenset(
    {"gamma", "beta", "moving_mean", "moving_var", "pos_embed"})
#: module-name fragments of the repo's norm layers: make_norm's ``bn*`` /
#: ``downsample_bn`` (FrozenBN + GroupNorm) and the transformer
#: ``norm*`` / ``dec_norm`` LayerNorms (models/vit.py, models/detr.py) —
#: plus DETR's set-prediction heads (``class_embed`` / ``bbox_mlp*`` /
#: ``bbox_out``), which are declared ``dtype=jnp.float32`` Denses over
#: ``island(hs)``: flax computes them with UNCAST f32 weights in tree
#: mode (no per-use cast for the shadow to commute with), so a bf16
#: shadow view would silently quantize exactly the box/score numerics
#: the island contract promises stay f32.
#: ``_ln`` covers the SFP upsampling LayerNorm (models/vit.py up4_ln)
_ISLAND_MODULES = ("bn", "norm", "_ln", "class_embed", "bbox_mlp",
                   "bbox_out")


def normalize_compute_dtype(value: str) -> str:
    """Knob spelling → canonical dtype name; raises on anything else."""
    key = str(value).strip().lower()
    if key not in _CANON:
        raise ValueError(
            f"train.compute_dtype must be one of "
            f"{sorted(set(_CANON))}, got {value!r}")
    return _CANON[key]


@dataclass(frozen=True)
class Policy:
    """Resolved dtype policy: ``compute`` is what the forward/backward
    run in, ``master`` what parameters/gradients/optimizer state are
    stored and updated in (always float32 here — bf16 master weights are
    a different, accuracy-risky regime this repo does not offer)."""

    compute: str  # canonical dtype name ("float32" | "bfloat16")
    master: str = "float32"

    @property
    def mixed(self) -> bool:
        return self.compute != self.master

    @property
    def compute_jnp(self):
        return jnp.dtype(self.compute)

    @property
    def short(self) -> str:
        """Ledger/bench row spelling ("f32" / "bf16")."""
        return SHORT[self.compute]


def policy_of(cfg) -> Policy:
    """The run's policy from ``cfg.train.compute_dtype`` (validated)."""
    return Policy(compute=normalize_compute_dtype(cfg.train.compute_dtype))


def model_dtype(cfg):
    """The flax-module ``dtype`` the policy implies — every build_model
    variant reads the knob through here (models/*.py)."""
    return policy_of(cfg).compute_jnp


def island(x: jnp.ndarray) -> jnp.ndarray:
    """THE sanctioned f32 island cast for model code: losses, norm
    statistics, bbox_transform encode/decode, NMS scores. Routing the
    cast through here (instead of a scattered ``.astype(jnp.float32)``)
    keeps the island set auditable — the ``dtype-cast-in-jit`` lint rule
    flags hard-coded float dtype literals in model code."""
    return x.astype(jnp.float32)


def is_island_param(path: str) -> bool:
    """True for param leaves that must stay f32 VIEWS of the master
    buffer under a bf16 policy: norm statistics and norm affine terms.

    ``path`` is the flatcore segment path ("/"-joined tree keys, e.g.
    ``params/features/stage2/block0/bn1/scale``). Everything else (conv/
    dense kernels and biases) reads the compute shadow."""
    parts = path.split("/")
    if parts and parts[-1] in _ISLAND_LEAVES:
        return True
    # the owning module: norm layers are named bn*/downsample_bn (ResNet/
    # VGG families) and norm*/dec_norm (ViT/DETR LayerNorms)
    if len(parts) >= 2:
        module = parts[-2]
        if any(frag in module for frag in _ISLAND_MODULES):
            return True
    return False


def cast_buffers(bufs, dtype):
    """{name: buffer} → same dict with every FLOAT buffer cast to
    ``dtype`` — exactly one ``convert`` per float buffer (the flatcore
    compute-shadow materialization). Non-float buffers pass through."""
    dtype = jnp.dtype(dtype)
    out = {}
    for name, buf in bufs.items():
        if jnp.issubdtype(buf.dtype, jnp.floating) and buf.dtype != dtype:
            out[name] = buf.astype(dtype)
        else:
            out[name] = buf
    return out
