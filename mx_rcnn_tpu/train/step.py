"""The pjit-able train step — MutableModule.fit's hot loop, TPU-style.

Reference: rcnn/core/module.py MutableModule + the per-batch loop in
train_end2end.py (SURVEY.md §4.1): forward_backward → KVStore push/pull →
update. Here the whole thing is ONE jitted SPMD program: loss+grad,
XLA-inserted gradient allreduce over the mesh `data` axis, optax update.

No rebinding: the reference rebinds executors when a batch outgrows the bound
shapes (MutableModule's raison d'être); static padded shapes make that
machinery unnecessary — one compilation per config, period.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN, forward_train
from mx_rcnn_tpu.resilience import chaos
from mx_rcnn_tpu.train import health as health_mod


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def create_train_state(params, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        tx=tx,
    )


def _metric_parts(aux: Dict[str, jnp.ndarray]) -> Dict[str, tuple]:
    """The reference's 6 metrics (rcnn/core/metric.py) as (num, den) pairs
    so they pool EXACTLY across micro-steps: losses are (value, 1) means;
    accuracies are (correct-count, valid-count) — summing parts then
    dividing gives the big-batch value, which a mean-of-ratios would not.

    RPNAcc/RCNNAcc ignore label −1 exactly as the reference metrics mask
    ignore labels. Tolerant of partial aux (rpn-only / rcnn-only stages
    emit their half; DETR emits rcnn_* losses without logits).
    """
    one = jnp.ones((), jnp.float32)
    out = {"TotalLoss": (aux["total_loss"], one)}
    if "rpn_cls_loss" in aux:
        out["RPNLogLoss"] = (aux["rpn_cls_loss"], one)
        out["RPNL1Loss"] = (aux["rpn_bbox_loss"], one)
    if "rpn_logits" in aux:
        rpn_pred = jnp.argmax(aux["rpn_logits"], axis=-1)
        rpn_valid = aux["rpn_labels"] >= 0
        rpn_correct = (rpn_pred == aux["rpn_labels"]) & rpn_valid
        out["RPNAcc"] = (jnp.sum(rpn_correct).astype(jnp.float32),
                         jnp.sum(rpn_valid).astype(jnp.float32))
    if "rcnn_cls_loss" in aux:
        out["RCNNLogLoss"] = (aux["rcnn_cls_loss"], one)
        out["RCNNL1Loss"] = (aux["rcnn_bbox_loss"], one)
    if "rcnn_logits" in aux:
        rcnn_pred = jnp.argmax(aux["rcnn_logits"], axis=-1)
        rcnn_valid = aux["rcnn_labels"] >= 0
        rcnn_correct = (rcnn_pred == aux["rcnn_labels"]) & rcnn_valid
        out["RCNNAcc"] = (jnp.sum(rcnn_correct).astype(jnp.float32),
                          jnp.sum(rcnn_valid).astype(jnp.float32))
    return out


def _finalize_metrics(parts: Dict[str, tuple]) -> Dict[str, jnp.ndarray]:
    return {k: num / (den + 1e-12) for k, (num, den) in parts.items()}


def _metrics_from_aux(aux: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    return _finalize_metrics(_metric_parts(aux))


def make_train_step(
    model: FasterRCNN,
    cfg: Config,
    mesh: Optional[Mesh] = None,
    donate: bool = True,
    forward_fn: Callable = forward_train,
    param_specs=None,
    flat_core=None,
    health: bool = False,
) -> Callable[[TrainState, Dict[str, jnp.ndarray], jax.Array],
              Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Build the jitted train step.

    With a mesh: params/opt_state replicated, batch sharded on `data` —
    XLA inserts the gradient all-reduce over ICI (the KVStore replacement).
    Without: plain single-device jit. forward_fn selects the training graph
    (end2end / rpn-only / rcnn-only — the reference's get_*_train symbol
    variants).

    graftcanvas (image.canvas_pack): packed batches shard/accumulate
    UNCHANGED through this machinery — every leaf's leading dim is the
    plane count P (one-plus planes per data shard; im_info/gt tensors are
    (P, I, ...)), so the P('data') sharding, the accum inner-reshape and
    multi-step stacking all slice whole planes. The forward detects the
    packed contract from the batch itself (ops/canvas.py).

    cfg.train.multi_step_dispatch = K > 1 returns a MULTI-step function:
    it takes step-stacked batches (leaves (K, B, ...), sharded
    P(None, 'data')) and performs K full optimizer steps in one
    lax.scan-ed program — one host dispatch pays the fixed relay/dispatch
    overhead for K steps. Metrics are pooled over the K steps.

    param_specs (parallel/partition.py): tensor-parallel weight shardings.
    The state must then arrive PRE-PLACED (shard_train_state) — shardings
    are inferred from the committed inputs and propagated by GSPMD, which
    inserts the TP collectives alongside the data-axis gradient psum.

    flat_core (train/flatcore.py): state is a FlatTrainState; the loss is
    differentiated with respect to the FLAT BUFFERS — the param tree the
    forward sees is slice/reshape views materialized in-graph, so the
    backward writes one flat gradient per dtype and the DP allreduce is
    one psum per buffer. Donation, grad accumulation and multi-step
    dispatch compose unchanged (the flat state is an ordinary pytree).

    graftcast (train.compute_dtype=bf16 + flat_core): the differentiated
    value is the (master, compute-shadow) buffer PAIR — the forward's
    views slice the bf16 shadow (f32 islands slice the master), the
    shadow cotangent is cast up once per buffer inside
    FlatCore.master_grads, and the update re-materializes the shadow
    from the new masters (one cast per buffer, a program output). Tree
    mode under bf16 keeps flax's per-leaf promotion — same values.

    graftpulse (health=True, obs.health_every > 0): the step RETURNS a
    third output — the numerics health dict of train/health.py
    (per-flat-buffer / whole-tree nonfinite counts and squared norms of
    grads, params and the update delta, plus the pooled loss) — computed
    in-graph and fused into the same executable, so the cadenced host
    read (obs/health.py) adds no per-step sync and no extra compile.
    health=False keeps the exact two-output program (bit-identical HLO
    to pre-graftpulse). Chaos ``nan_at_step=K`` (resilience/chaos.py)
    poisons step K's final gradients IN-GRAPH here, after the accum fold
    and the bf16 cast-up — the registered "grad_inject" site, traced in
    at build time.
    """

    accum = max(1, int(getattr(cfg.train, "grad_accum_steps", 1)))
    multi = max(1, int(getattr(cfg.train, "multi_step_dispatch", 1)))
    # graftpulse chaos: the spec is env-carried and static per process —
    # parse once at build time; the injection (if armed) is traced into
    # the step at the registered "grad_inject" site below.
    _spec = chaos.from_env()
    nan_at = int(_spec.nan_at_step)
    if _spec.active:
        _spec.fire("grad_inject")
    # graftpulse flat-mode CPU quirk (train/health.py::step_health): the
    # probed gradient buffers must be program OUTPUTS on the CPU backend
    # or XLA schedules the backward ~8x slower; pinning under a scan
    # (multi-step) would stack K grad-sized buffers instead, so the pin
    # is single-step only.
    pin_grads = (health and flat_core is not None and multi == 1
                 and jax.default_backend() == "cpu")
    if flat_core is not None:
        def as_params(diff):
            return flat_core.params_view(*diff) if flat_core.policy.mixed \
                else flat_core.table.unflatten(diff)
    else:
        def as_params(diff):
            return diff

    def _grads_of(diff, chunk, key):
        def loss_fn(p):
            loss, aux = forward_fn(model, as_params(p), chunk, key, cfg)
            return loss, aux

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(diff)
        if flat_core is not None and flat_core.policy.mixed:
            # Cast the shadow cotangent up and fold it into the f32
            # master gradient HERE, per micro-step: accumulation, the DP
            # psum and the update all run float32 from this point on.
            grads = flat_core.master_grads(grads)
        return grads, _metric_parts(aux)

    def _diff_of(state):
        if flat_core is None:
            return state.params
        if flat_core.policy.mixed:
            # graftcast: differentiate the (master, shadow) pair — island
            # grads land f32 in the master cotangent, the bf16 shadow
            # cotangent is cast up once per buffer (FlatCore.master_grads)
            return (state.flat, state.compute)
        return state.flat

    def _one_update(state: TrainState, batch, rng):
        if accum == 1:
            grads, parts = _grads_of(_diff_of(state), batch, rng)
        else:
            # Micro-step accumulation: the batch's leading dim is
            # accum x micro-batch; grads average and metric PARTS sum
            # (pooled accuracies = big-batch values) — identical gradient
            # semantics to the big batch (per-image-normalized losses;
            # frozen-BN / GroupNorm have no cross-batch coupling) at
            # 1/accum of the activation memory. Accum is the INNER dim of
            # the reshape so every chunk keeps one-row-per-device under
            # the data mesh (outer would hand each chunk to a device
            # subset and reshard every micro-step). The loop is UNROLLED
            # (accum is a small static int): a lax.scan body holding the
            # full fwd+bwd makes the SPMD partitioner pathologically slow
            # to compile (measured >12 min for accum=2 at 64^2 on CPU;
            # unrolled: seconds).
            chunks = jax.tree.map(
                lambda x: x.reshape(x.shape[0] // accum, accum,
                                    *x.shape[1:]), batch)
            keys = jax.random.split(rng, accum)
            g_tot, p_tot = None, None
            for i in range(accum):
                chunk = jax.tree.map(lambda x: x[:, i], chunks)
                g, p = _grads_of(_diff_of(state), chunk, keys[i])
                if g_tot is None:
                    g_tot, p_tot = g, p
                else:
                    g_tot = jax.tree.map(jnp.add, g_tot, g)
                    p_tot = jax.tree.map(jnp.add, p_tot, p)
            grads = jax.tree.map(lambda g: g / accum, g_tot)
            parts = p_tot
        if nan_at:
            # chaos nan_at_step: poison the FINAL gradients (post accum
            # fold / cast-up) of the armed optimizer step, in-graph.
            grads = chaos.poison_grads(grads, state.step, nan_at)
        new_state = state.apply_gradients(grads)
        if not health:
            return new_state, parts
        num, den = parts["TotalLoss"]
        return new_state, parts, health_mod.step_health(
            state, grads, new_state, flat_core, num / (den + 1e-12),
            pin_grads=pin_grads)

    if multi == 1:
        def step(state: TrainState, batch, rng):
            if health:
                new_state, parts, pulse = _one_update(state, batch, rng)
                return new_state, _finalize_metrics(parts), pulse
            new_state, parts = _one_update(state, batch, rng)
            return new_state, _finalize_metrics(parts)
    else:
        # Multi-step dispatch: K full optimizer steps per host call via
        # lax.scan over step-stacked batches (leaves (K, B, ...)) — the
        # fixed per-dispatch overhead is paid once per K steps. Metric
        # PARTS sum across the K steps before finalizing, so the returned
        # metrics are the pooled values over all K·B images (identical
        # accounting to K separate Speedometer updates).
        def step(state: TrainState, batches, rng):
            keys = jax.random.split(rng, multi)

            def body(st, xs):
                chunk, key = xs
                if health:
                    st, parts, pulse = _one_update(st, chunk, key)
                    return st, (parts, pulse)
                st, parts = _one_update(st, chunk, key)
                return st, parts

            if health:
                state, (parts_seq, h_seq) = jax.lax.scan(
                    body, state, (batches, keys))
                parts = jax.tree.map(lambda x: jnp.sum(x, axis=0),
                                     parts_seq)
                # nonfinite counts sum over the K steps; norms/loss keep
                # the last step's row (train/health.py).
                return (state, _finalize_metrics(parts),
                        health_mod.fold_multi_step(h_seq))
            state, parts_seq = jax.lax.scan(body, state, (batches, keys))
            parts = jax.tree.map(lambda x: jnp.sum(x, axis=0), parts_seq)
            return state, _finalize_metrics(parts)

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    if param_specs is not None:
        # TP: respect the committed shardings of state (mixed sharded/
        # replicated leaves) and batch; outputs keep propagated layouts.
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("data") if multi == 1
                            else P(None, "data"))
    return jax.jit(
        step,
        in_shardings=(repl, data_sh, repl),
        out_shardings=(repl, repl, repl) if health else (repl, repl),
        donate_argnums=(0,) if donate else (),
    )
