"""Model/data plumbing utilities.

Reference: rcnn/utils/ — load_data.py (covered by data/datasets + tools),
load_model.py (pretrained.py ImageNet import + train/checkpoint.py),
save_model.py (train/checkpoint.py), combine_model.py (here).
"""

from mx_rcnn_tpu.utils.combine_model import combine_model
from mx_rcnn_tpu.utils.pretrained import import_pretrained

__all__ = ["combine_model", "import_pretrained"]
