"""Model/data plumbing utilities.

Reference: rcnn/utils/ — load_data.py (covered by data/datasets + tools),
load_model.py / save_model.py (covered by train/checkpoint.py),
combine_model.py (here).
"""

from mx_rcnn_tpu.utils.combine_model import combine_model

__all__ = ["combine_model"]
