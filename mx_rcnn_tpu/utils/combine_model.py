"""Combine RPN + RCNN stage parameters into one detector.

Reference: rcnn/utils/combine_model.py — after 4-stage alternate training,
merges the stage-2 RPN checkpoint (conv trunk + rpn head) with the stage-2
RCNN checkpoint (box head + cls/bbox FCs) into the final .params pair.

Param-tree layout (models/faster_rcnn.FasterRCNN):
  params/features   — conv trunk      ← RPN checkpoint (shared, frozen in
  params/rpn        — RPN head        ← RPN checkpoint   stage 2 so both
  params/head       — stage5/fc head  ← RCNN checkpoint  stages agree)
  params/cls_score, params/bbox_pred  ← RCNN checkpoint
"""

from __future__ import annotations

RPN_KEYS = ("features", "rpn")
RCNN_KEYS = ("head", "cls_score", "bbox_pred")


def combine_model(rpn_params, rcnn_params):
    """Merge two full parameter trees subtree-by-subtree."""
    rpn_p = rpn_params["params"]
    rcnn_p = rcnn_params["params"]
    merged = {}
    for k in rpn_p:
        if k in RPN_KEYS:
            merged[k] = rpn_p[k]
        elif k in RCNN_KEYS:
            merged[k] = rcnn_p[k]
        else:  # unknown subtree: prefer the rcnn stage (newest training)
            merged[k] = rcnn_p[k]
    return {"params": merged}
