"""Persistent XLA compilation cache for the CLI entry points.

Found by the r5 on-disk rehearsal: the test suite, bench.py, and
__graft_entry__.py all share tests/.jax_cache, but the USER-FACING entry
points (train_end2end.py, test.py, train_alternate.py, demo.py) never
enabled a cache — every invocation recompiled identical programs from
scratch (~70-147 s/program on the TPU relay, tens of minutes on CPU).
A --resume restart after a crash paid the full compile again, which
defeats the point of fast recovery.

Default location: <repo>/tests/.jax_cache (the same cache the suite
warms); override with MXRCNN_COMPILE_CACHE=<dir>, disable with
MXRCNN_COMPILE_CACHE=0.
"""

from __future__ import annotations

import os


def enable_persistent_cache() -> None:
    import jax

    loc = os.environ.get("MXRCNN_COMPILE_CACHE", "")
    if loc == "0":
        return
    if not loc:
        # Repo-checkout default (shared with the test suite); fall back
        # to a user cache dir when the source tree is not writable
        # (installed package / read-only checkout) — an unwritable cache
        # dir would just spam warnings and never speed anything up.
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        loc = os.path.join(repo, "tests", ".jax_cache")
        if not os.access(os.path.join(repo, "tests")
                         if os.path.isdir(os.path.join(repo, "tests"))
                         else repo, os.W_OK):
            loc = os.path.join(os.path.expanduser("~"), ".cache",
                               "mxrcnn", "jax")
    jax.config.update("jax_compilation_cache_dir", loc)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
