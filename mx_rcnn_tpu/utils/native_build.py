"""Shared build-on-first-use machinery for the C accelerator kernels.

One place for the concerns both ctypes bridges (masks/_native.py,
data/_native_img.py) need:

- build with the system compiler into a TEMP file and atomically rename —
  concurrent first-use builds (loader worker threads start immediately)
  cannot interleave writes into a corrupt .so that would permanently
  disable the native path;
- a process-wide lock around the build/load bootstrap;
- staleness: rebuild when the source is newer than the .so (an edited
  kernel with a stale artifact would otherwise run old code or blow up
  on a missing symbol);
- load failures of ANY kind return None — callers keep their numpy
  fallback, the native layer is a pure accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

_LOCK = threading.Lock()


def _build(src: str, so: str) -> Optional[str]:
    os.makedirs(os.path.dirname(so), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(so))
    os.close(fd)
    try:
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)  # atomic: readers see old or new
                return so
            except (OSError, subprocess.SubprocessError):
                continue
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def build_and_load(src: str, so: str, bind) -> Optional[ctypes.CDLL]:
    """Return the bound CDLL for ``src`` (building/rebuilding ``so`` as
    needed), or None when the toolchain/artifact is unusable.

    ``bind(lib)`` declares restype/argtypes for every symbol; if it
    raises (stale .so missing a symbol), the library is rebuilt once
    from source before giving up.
    """
    with _LOCK:
        if not os.path.exists(src):
            return None

        def fresh(path: str) -> bool:
            try:
                return os.path.getmtime(path) >= os.path.getmtime(src)
            except OSError:
                return False

        path = so if fresh(so) else _build(src, so)
        if path is None:
            return None
        for attempt in (0, 1):
            try:
                lib = ctypes.CDLL(path)
                bind(lib)
                return lib
            except (OSError, AttributeError):
                if attempt == 0:  # corrupt or stale artifact: rebuild once
                    path = _build(src, so)
                    if path is None:
                        return None
        return None
