"""Pretrained-weight import: npz manifest ↔ flax param tree.

Reference: rcnn/utils/load_model.py::load_param over ImageNet ``.params``
files + script/get_pretrained_model.sh (SURVEY.md §3). The reference
initializes the shared conv trunk (and, for VGG, fc6/fc7) from an ImageNet
classification checkpoint and random-inits the new heads (rpn_*, cls_score,
bbox_pred); frozen BatchNorm is only sound with the pretrained moving
statistics restored. This module is that import path for the TPU build.

## The npz manifest (documented contract — see BASELINE.md)

A pretrained file is a ``.npz`` holding a flat dict: key = ``/``-joined path
of a param leaf, value = numpy array **already in this build's layouts**
(conv kernels HWIO, dense kernels (in, out), NHWC flatten order for VGG
fc6). Keys may be either

- **backbone-relative** (the canonical manifest produced by
  ``utils/torch_convert.py``): no ``features/`` prefix, e.g.::

      conv0/kernel                      (7,7,3,64)      ResNet stem
      bn0/gamma|beta|moving_mean|moving_var   (64,)
      stage{1..4}/block{i}/conv{1,2,3}/kernel
      stage{1..4}/block{i}/bn{1,2,3}/gamma|beta|moving_mean|moving_var
      stage{1..4}/block0/downsample_conv/kernel + downsample_bn/*
      conv{b}_{c}/kernel|bias           VGG-16 13 convs
      fc6/kernel|bias, fc7/kernel|bias  VGG classifier (reference loads
                                        these into the detection head too)

  Routing: each key is tried at ``<key>``, ``features/<key>`` then
  ``head/<key>`` in the detector tree — which places ResNet ``stage4``
  under ``features/`` for FPN models and under ``head/`` for C4 models,
  and VGG ``fc6/fc7`` under ``head/``, with no per-family tables.

- **full-tree** paths (``features/...``, ``head/...``, ``rpn/...``, ...):
  matched verbatim; lets an npz round-trip a whole detector.

Keys with no destination in the template (e.g. the ImageNet ``fc_final``
classifier, or ResNet ``stage4`` when the model is C4-with-FPN-neck) are
reported, not fatal. Template leaves the npz does not cover keep their
fresh initialization — by design for ``rpn_*``/``cls_score``/``bbox_pred``
(reference behavior), and validated for the backbone: ``strict_backbone``
(default) raises if any ``features/`` leaf stays uninitialized, since a
silently half-loaded trunk is the classic silent-mAP-killer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np
from flax import traverse_util

from mx_rcnn_tpu.logger import logger


def flatten_params(tree: Dict) -> Dict[str, np.ndarray]:
    """Nested dict tree → {'a/b/c': leaf} (flax flatten_dict, sep='/').
    No-op on an already-flat manifest dict."""
    if not tree:
        return {}
    return traverse_util.flatten_dict(tree, sep="/")


def unflatten_params(flat: Dict[str, np.ndarray]) -> Dict:
    return traverse_util.unflatten_dict(flat, sep="/")


def save_params_npz(path: str, tree_or_flat) -> None:
    """Save a param tree (or an already-flat manifest dict) as an npz."""
    flat = flatten_params(tree_or_flat)  # no-op on an already-flat dict
    np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})


def load_params_npz(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as data:
        return {k: data[k] for k in data.files}


@dataclass
class ImportReport:
    loaded: List[str] = field(default_factory=list)      # template paths set
    unused: List[str] = field(default_factory=list)      # npz keys w/o a home
    skipped: List[str] = field(default_factory=list)     # shape-mismatch heads
    uninitialized: List[str] = field(default_factory=list)  # template leaves kept

    def summary(self) -> str:
        return (f"loaded {len(self.loaded)} leaves; "
                f"{len(self.unused)} npz keys unused; "
                f"{len(self.skipped)} skipped (shape mismatch); "
                f"{len(self.uninitialized)} template leaves left at init")


# New-head leaves the reference random-inits; a class-count mismatch there
# is expected (ImageNet→COCO), anywhere else it is an error.
_HEAD_PREFIXES = ("cls_score", "bbox_pred", "rpn/")


def import_pretrained(npz_path: str, template: Dict,
                      strict_backbone: bool = True) -> Tuple[Dict, ImportReport]:
    """Merge a manifest npz into a fresh param tree (see module docstring).

    template: the ``init_params`` tree (either the bare param dict or one
    wrapped in {'params': ...}). Returns (params, report) with the same
    wrapping as the template. Leaf dtypes follow the template.
    """
    wrapped = isinstance(template, dict) and set(template) == {"params"}
    tree = template["params"] if wrapped else template
    flat = flatten_params(tree)
    npz = load_params_npz(npz_path)

    report = ImportReport()
    out = dict(flat)
    for key, val in sorted(npz.items()):
        for dest in (key, f"features/{key}", f"head/{key}"):
            if dest in flat:
                break
        else:
            report.unused.append(key)
            continue
        want = np.asarray(flat[dest]).shape
        if tuple(val.shape) != tuple(want):
            if any(dest.startswith(p) for p in _HEAD_PREFIXES):
                # Reference load_param: detection heads with a different
                # class count keep their fresh init.
                report.skipped.append(f"{key} -> {dest} "
                                      f"(npz {val.shape} vs model {want})")
                continue
            raise ValueError(
                f"pretrained import: {npz_path!r} key {key!r} maps to "
                f"{dest!r} but shapes differ (npz {tuple(val.shape)} vs "
                f"model {tuple(want)}) — wrong depth/backbone manifest?")
        out[dest] = np.asarray(val, dtype=np.asarray(flat[dest]).dtype)
        report.loaded.append(dest)

    loaded_set = set(report.loaded)
    report.uninitialized = [k for k in flat if k not in loaded_set]
    # The trunk is everything ImageNet init covers: features/* plus the
    # C4 stage4 that lives under head/ — a partially initialized trunk
    # passes training but silently kills mAP. head/fc* is ambiguous (VGG's
    # fc6/fc7 come from ImageNet; ResNet-FPN's same-named 2-FC box head is
    # a new head), so an uncovered fc head only warns.
    missing_bb = [k for k in report.uninitialized
                  if k.startswith(("features/", "head/stage"))]
    missing_fc = [k for k in report.uninitialized if k.startswith("head/fc")]
    if missing_fc and any(k.startswith("fc") for k in npz):
        logger.warning(
            "pretrained import: npz provides fc keys but %d head/fc leaves "
            "stayed at init (e.g. %s) — shape mismatch? For VGG this "
            "forfeits the ImageNet fc6/fc7 init.", len(missing_fc),
            missing_fc[:2])
    if strict_backbone and missing_bb:
        raise ValueError(
            f"pretrained import: {len(missing_bb)} backbone leaves not "
            f"covered by {npz_path!r} (e.g. {missing_bb[:4]}) — a partially "
            "initialized trunk trains but silently kills mAP. Pass "
            "strict_backbone=False only if this is intentional.")
    if not report.loaded:
        raise ValueError(
            f"pretrained import: no key in {npz_path!r} matched the model "
            f"tree (sample npz keys: {sorted(npz)[:4]})")
    logger.info("pretrained import from %s: %s", npz_path, report.summary())

    merged = unflatten_params(out)
    return ({"params": merged} if wrapped else merged), report
