"""Convert torch/torchvision ImageNet checkpoints → the npz manifest.

Reference: script/get_pretrained_model.sh downloads MXNet ``.params``
ImageNet checkpoints consumed by rcnn/utils/load_model.py::load_param.
MXNet-format files cannot exist in this environment; the publicly
obtainable equivalents are torchvision's ``resnet50/101`` and ``vgg16``
ImageNet state_dicts, so this converter targets that naming scheme
(plain ``state_dict()`` key/value dicts — a ``.pth`` file or in-memory).

Layout conversions performed (torch → this build):
- conv weights  (O, I, kH, kW) → HWIO (kH, kW, I, O)
- linear weights (out, in)     → (in, out)
- BatchNorm weight/bias/running_mean/running_var
    → gamma/beta/moving_mean/moving_var
- VGG fc6: torch flattens pool5 as (C=512, H=7, W=7); this build pools
  NHWC and flattens as (H, W, C). The input axis is permuted to match —
  without this the loaded fc6 is a channel-scrambled near-no-op.

Name maps:
- ResNet: conv1→conv0, bn1→bn0, layer{s}.{i}→stage{s}/block{i},
  conv{k}/bn{k} kept, downsample.0/.1→downsample_conv/downsample_bn.
  (fc.* — the ImageNet classifier — is dropped.)
- VGG-16: features.{0,2,5,7,10,12,14,17,19,21,24,26,28}
  → conv{1_1 .. 5_3}; classifier.0→fc6, classifier.3→fc7
  (classifier.6 — the ImageNet classifier — is dropped.)

Usage (CLI)::

    python -m mx_rcnn_tpu.utils.torch_convert resnet101 \
        resnet101-imagenet.pth model/resnet101.npz
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from mx_rcnn_tpu.utils.pretrained import save_params_npz

# torchvision vgg16 feature-extractor conv layer indices, in order.
_VGG16_CONV_IDX = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28)
_VGG16_PLAN = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))

_BN_LEAF = {"weight": "gamma", "bias": "beta",
            "running_mean": "moving_mean", "running_var": "moving_var"}


def _np(t) -> np.ndarray:
    """torch.Tensor or array-like → float32 numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv(w) -> np.ndarray:
    return _np(w).transpose(2, 3, 1, 0)  # OIHW -> HWIO


def convert_torchvision_resnet(state_dict: Dict) -> Dict[str, np.ndarray]:
    """torchvision resnet50/101 state_dict → backbone-manifest flat dict."""
    out: Dict[str, np.ndarray] = {}
    for key, val in state_dict.items():
        parts = key.split(".")
        if parts[-1] == "num_batches_tracked" or parts[0] == "fc":
            continue
        if parts[0] == "conv1":
            out["conv0/kernel"] = _conv(val)
        elif parts[0] == "bn1":
            out[f"bn0/{_BN_LEAF[parts[1]]}"] = _np(val)
        elif parts[0].startswith("layer"):
            stage = int(parts[0][len("layer"):])
            base = f"stage{stage}/block{int(parts[1])}"
            mod = parts[2]
            if mod.startswith("conv"):
                out[f"{base}/{mod}/kernel"] = _conv(val)
            elif mod.startswith("bn"):
                out[f"{base}/{mod}/{_BN_LEAF[parts[3]]}"] = _np(val)
            elif mod == "downsample":  # layerS.B.downsample.{0,1}.<leaf>
                idx, leaf = parts[3], parts[4]
                if idx == "0":
                    out[f"{base}/downsample_conv/kernel"] = _conv(val)
                else:
                    out[f"{base}/downsample_bn/{_BN_LEAF[leaf]}"] = _np(val)
            else:
                raise KeyError(f"unrecognized resnet key {key!r}")
        else:
            raise KeyError(f"unrecognized resnet key {key!r}")
    return out


def convert_torchvision_vgg16(state_dict: Dict) -> Dict[str, np.ndarray]:
    """torchvision vgg16 state_dict → backbone-manifest flat dict
    (13 convs + fc6/fc7, with the fc6 flatten-order permute)."""
    names = []
    for b, (n_convs, _w) in enumerate(_VGG16_PLAN, start=1):
        names += [f"conv{b}_{c}" for c in range(1, n_convs + 1)]
    idx_to_name = dict(zip(_VGG16_CONV_IDX, names))

    out: Dict[str, np.ndarray] = {}
    for key, val in state_dict.items():
        parts = key.split(".")
        if parts[0] == "features":
            name = idx_to_name.get(int(parts[1]))
            if name is None:
                raise KeyError(f"unrecognized vgg16 conv index in {key!r}")
            out[f"{name}/kernel" if parts[2] == "weight"
                else f"{name}/bias"] = (
                _conv(val) if parts[2] == "weight" else _np(val))
        elif parts[0] == "classifier":
            idx, leaf = int(parts[1]), parts[2]
            if idx == 6:
                continue  # ImageNet 1000-way classifier
            name = {0: "fc6", 3: "fc7"}[idx]
            if leaf == "bias":
                out[f"{name}/bias"] = _np(val)
            elif name == "fc6":
                # (4096, 25088) over (C,H,W) flatten → (25088, 4096) over
                # (H,W,C) flatten.
                w = _np(val).reshape(4096, 512, 7, 7)
                out["fc6/kernel"] = (
                    w.transpose(2, 3, 1, 0).reshape(7 * 7 * 512, 4096))
            else:
                out[f"{name}/kernel"] = _np(val).T
        else:
            raise KeyError(f"unrecognized vgg16 key {key!r}")
    return out


CONVERTERS = {
    "resnet50": convert_torchvision_resnet,
    "resnet101": convert_torchvision_resnet,
    "vgg16": convert_torchvision_vgg16,
    "vgg": convert_torchvision_vgg16,
}


def convert(arch: str, state_dict: Dict, out_npz: str) -> Dict[str, np.ndarray]:
    flat = CONVERTERS[arch](state_dict)
    save_params_npz(out_npz, flat)
    return flat


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("arch", choices=sorted(CONVERTERS))
    p.add_argument("pth", help="torch state_dict file (.pth)")
    p.add_argument("out", help="output .npz manifest path")
    args = p.parse_args(argv)

    import torch

    sd = torch.load(args.pth, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    flat = convert(args.arch, sd, args.out)
    print(f"wrote {args.out}: {len(flat)} arrays")


if __name__ == "__main__":
    main()
