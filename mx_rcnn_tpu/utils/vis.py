"""Detection visualization (reference: the vis branch of tester.py::pred_eval
and demo.py's drawing) — pure-numpy rectangles + PIL save, no cv2 needed."""

from __future__ import annotations

import os

import numpy as np

from mx_rcnn_tpu.logger import logger


def draw_detections(img_uint8: np.ndarray, dets: np.ndarray,
                    class_names) -> np.ndarray:
    """Overlay (n, 6) [cls, score, x1, y1, x2, y2] detections on an RGB
    uint8 image."""
    out = img_uint8.copy()
    for d in dets:
        cls, score = int(d[0]), d[1]
        x1, y1, x2, y2 = (int(round(v)) for v in d[2:6])
        x1, y1 = max(x1, 0), max(y1, 0)
        x2 = min(x2, out.shape[1] - 1)
        y2 = min(y2, out.shape[0] - 1)
        color = np.array([255, 50, 50], np.uint8)
        out[y1:y2 + 1, x1:x1 + 3] = color
        out[y1:y2 + 1, x2 - 2:x2 + 1] = color
        out[y1:y1 + 3, x1:x2 + 1] = color
        out[y2 - 2:y2 + 1, x1:x2 + 1] = color
        name = class_names[cls] if cls < len(class_names) else str(cls)
        logger.info("det %s score=%.3f box=(%d,%d,%d,%d)",
                    name, score, x1, y1, x2, y2)
    return out


def save_vis(img_uint8: np.ndarray, dets: np.ndarray, class_names,
             path: str) -> bool:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    vis = draw_detections(img_uint8, dets, class_names)
    try:
        from PIL import Image

        Image.fromarray(vis).save(path)
        return True
    except (ImportError, OSError, ValueError,
            TypeError) as exc:  # pragma: no cover
        # TypeError: PIL's "Cannot handle this data type" for non-uint8
        # input — part of the best-effort False contract, not a crash.
        logger.warning("could not save visualization %s: %s", path, exc)
        return False
