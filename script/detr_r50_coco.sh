#!/usr/bin/env bash
# DETR-R50 on COCO (stretch config 5): set prediction with in-graph auction
# matching, no NMS / anchors / proposals. DETR needs long schedules
# (~300+ epochs on real COCO); this recipe pins the flags, not the wall time.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network detr_r50 --dataset coco --image_set train2017 \
  --prefix model/detr_r50_coco --end_epoch 300 --lr 0.0001 --lr_step 200 \
  --tpu-mesh "${TPU_MESH:-8}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network detr_r50 --dataset coco --image_set val2017 \
  --prefix model/detr_r50_coco --epoch 300 \
  --out_json results/detr_r50_coco_dets.json ${COMMON_SET:-}
