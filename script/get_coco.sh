#!/usr/bin/env bash
# Fetch COCO 2017 images + annotations into data/coco (reference:
# script/get_coco.sh). Requires network access — this CI container is
# offline; the script is the pinned recipe for a connected machine.
# Layout consumed by mx_rcnn_tpu.data.datasets.coco:
#   data/coco/annotations/instances_{train,val}2017.json
#   data/coco/{train2017,val2017}/*.jpg
set -euo pipefail
mkdir -p data/coco && cd data/coco

for z in train2017.zip val2017.zip; do
  [ -d "${z%.zip}" ] || { curl -L -O "http://images.cocodataset.org/zips/$z"; unzip -q "$z"; }
done
[ -d annotations ] || {
  curl -L -O http://images.cocodataset.org/annotations/annotations_trainval2017.zip
  unzip -q annotations_trainval2017.zip
}
echo "COCO 2017 ready under data/coco"
