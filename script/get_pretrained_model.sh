#!/usr/bin/env bash
# Fetch ImageNet-pretrained backbones and convert them to the npz manifest
# consumed by --pretrained (reference: script/get_pretrained_model.sh, which
# downloaded MXNet .params files; here the public torchvision checkpoints
# are the source — utils/torch_convert.py does the layout conversion).
#
# Requires network access (this CI container is offline: the script is the
# pinned recipe for a connected machine).
set -euo pipefail

mkdir -p model
declare -A URLS=(
  [resnet50]=https://download.pytorch.org/models/resnet50-0676ba61.pth
  [resnet101]=https://download.pytorch.org/models/resnet101-63fe2227.pth
  [vgg16]=https://download.pytorch.org/models/vgg16-397923af.pth
)

for arch in resnet50 resnet101 vgg16; do
  pth="model/${arch}-imagenet.pth"
  [ -f "$pth" ] || curl -L -o "$pth" "${URLS[$arch]}"
  python -m mx_rcnn_tpu.utils.torch_convert "$arch" "$pth" "model/${arch}.npz"
done
echo "manifests ready: model/{resnet50,resnet101,vgg16}.npz"
