#!/usr/bin/env bash
# Fetch PASCAL VOC 2007(+2012) into data/VOCdevkit (reference:
# script/get_voc.sh). Requires network access — this CI container is
# offline; the script is the pinned recipe for a connected machine.
# Layout consumed by mx_rcnn_tpu.data.datasets.pascal_voc:
#   data/VOCdevkit/VOC2007/{Annotations,ImageSets,JPEGImages}
#   data/VOCdevkit/VOC2012/...
set -euo pipefail
mkdir -p data && cd data

BASE=http://host.robots.ox.ac.uk/pascal/VOC/voc2007
for f in VOCtrainval_06-Nov-2007.tar VOCtest_06-Nov-2007.tar; do
  [ -f "$f" ] || curl -L -O "$BASE/$f"
  tar -xf "$f"
done
if [ "${WITH_VOC2012:-0}" = "1" ]; then
  f=VOCtrainval_11-May-2012.tar
  [ -f "$f" ] || curl -L -O \
    http://host.robots.ox.ac.uk/pascal/VOC/voc2012/$f
  tar -xf "$f"
fi
echo "VOC ready under data/VOCdevkit"
