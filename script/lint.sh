#!/usr/bin/env bash
# graftlint gate — identical invocation locally, in pre-commit, and in any
# future CI. Exits non-zero on any non-baselined finding or stale baseline
# entry. Paths/config come from [tool.graftlint] in pyproject.toml; the
# pre-commit hook passes --changed-only (the call graph still spans the
# whole tree — only the per-file rule pass narrows). --stats prints
# per-rule finding counts and wall time on every run.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m mx_rcnn_tpu.analysis --stats "$@"
