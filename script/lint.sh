#!/usr/bin/env bash
# graftlint gate — identical invocation locally, in pre-commit, and in any
# future CI. Exits non-zero on any non-baselined finding or stale baseline
# entry. Paths/config come from [tool.graftlint] in pyproject.toml; the
# pre-commit hook runs it repo-wide (pass_filenames: false — cfg-contract
# and the baseline are global properties). Explicit paths lint a subset.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m mx_rcnn_tpu.analysis "$@"
