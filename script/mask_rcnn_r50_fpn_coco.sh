#!/usr/bin/env bash
# Mask R-CNN R-50-FPN on COCO instance segmentation — BASELINE.json config 4.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network resnet50_fpn_mask --dataset coco --image_set train2017 \
  --prefix model/mask_r50_fpn_coco --end_epoch 8 --lr 0.00125 --lr_step 6 \
  --set network.proposal_topk=exact \
  --tpu-mesh "${TPU_MESH:-8}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network resnet50_fpn_mask --dataset coco --image_set val2017 \
  --prefix model/mask_r50_fpn_coco --epoch 8 \
  --out_json results/mask_r50_fpn_coco_dets.json ${COMMON_SET:-}
