#!/usr/bin/env bash
# Pack COCO train2017 into pre-decoded shards (one set per training
# scale), then any train recipe can add --packed-dir to use the fast
# host input path (553 vs 72 img/s, PERF.md r4). Run get_coco.sh first.
set -euxo pipefail
cd "$(dirname "$0")/.."

NETWORK="${NETWORK:-resnet101_fpn}"   # fixes the scales/pad buckets
OUT="${OUT:-data/packed/coco_train2017_${NETWORK}}"

JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.pack_dataset \
  --network "$NETWORK" --dataset coco --image_set train2017 \
  --out "$OUT" "$@"

echo "train with: train_end2end.py --network $NETWORK --dataset coco \\"
echo "  --image_set train2017 --packed-dir $OUT ..."
