#!/usr/bin/env bash
# ResNet-101 Faster R-CNN e2e on COCO train2017 — the BASELINE.json
# flagship C4 config. Expected ~26-27 box mAP@[.5:.95] (BASELINE.md).
# 8-way DP over one v5e host: TPU_MESH=8.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network resnet101 --dataset coco --image_set train2017 \
  --prefix model/r101_coco_e2e --end_epoch 8 --lr 0.00125 --lr_step 6 \
  --tpu-mesh "${TPU_MESH:-8}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network resnet101 --dataset coco --image_set val2017 \
  --prefix model/r101_coco_e2e --epoch 8 \
  --out_json results/r101_coco_dets.json ${COMMON_SET:-}
