#!/usr/bin/env bash
# ResNet-101-FPN Faster R-CNN e2e on COCO — BASELINE.json config 3
# (multi-scale FPN, 8-way DP).
#
# COMMON_SET: --set overrides that must reach BOTH the train and eval
# CLIs (anything that changes the model architecture — norm, freeze_at,
# channels — must match at eval or the checkpoint restore fails; found
# by the r5 on-disk rehearsal). Train-only flags go through "$@".
#   COMMON_SET="--set network.norm=group" script/resnet101_fpn_coco.sh ...
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network resnet101_fpn --dataset coco --image_set train2017 \
  --prefix model/r101_fpn_coco --end_epoch 8 --lr 0.00125 --lr_step 6 \
  --set network.proposal_topk=exact \
  --tpu-mesh "${TPU_MESH:-8}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network resnet101_fpn --dataset coco --image_set val2017 \
  --prefix model/r101_fpn_coco --epoch 8 \
  --out_json results/r101_fpn_coco_dets.json ${COMMON_SET:-}
