#!/usr/bin/env bash
# ResNet-101 Faster R-CNN e2e on VOC07+12, eval on VOC07 test.
# Reference recipe analog: script/resnet_voc0712.sh. Expected ~79 mAP@0.5.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network resnet101 --dataset PascalVOC \
  --image_set 2007_trainval+2012_trainval \
  --prefix model/r101_voc0712_e2e --end_epoch 10 --lr 0.001 --lr_step 7 \
  --tpu-mesh "${TPU_MESH:-1}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network resnet101 --dataset PascalVOC --image_set 2007_test \
  --prefix model/r101_voc0712_e2e --epoch 10 ${COMMON_SET:-}
