#!/usr/bin/env bash
# graftguard chaos gate — the fault-injection subset of tier-1 on CPU:
# injected UNAVAILABLE outages, SIGTERM preemption + kill->resume parity,
# hung-bench deadline isolation, and the checkpoint crash window
# (tests/test_resilience.py; runbook OUTAGES.md). Every failure mode the
# round-5 outage demonstrated, exercised on demand instead of by the next
# real outage. Same invocation locally and in any future CI.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu exec python -m pytest -m chaos "$@"
