#!/usr/bin/env bash
# graftguard + graftheal chaos gate — the fault-injection subset of tier-1
# on CPU: injected UNAVAILABLE outages, SIGTERM preemption + kill->resume
# parity, hung-bench deadline isolation, the checkpoint crash window
# (tests/test_resilience.py), and the graftheal matrix — mid-run device
# loss with heal-and-continue bit-exact parity (tree AND flat), double
# loss inside one heal window, elastic 8->4 shrink with loss-trajectory
# agreement, and cross-topology resume via the checkpoint meta sidecar
# (tests/test_heal.py). Runbook: OUTAGES.md. Every failure mode the
# round-5 outage demonstrated (and the mid-run one it implied), exercised
# on demand instead of by the next real outage. Same invocation locally
# and in any future CI.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu exec python -m pytest -m chaos "$@"
