#!/usr/bin/env bash
# graftguard + graftheal chaos gate — the fault-injection subset of tier-1
# on CPU: injected UNAVAILABLE outages, SIGTERM preemption + kill->resume
# parity, hung-bench deadline isolation, the checkpoint crash window
# (tests/test_resilience.py), and the graftheal matrix — mid-run device
# loss with heal-and-continue bit-exact parity (tree AND flat), double
# loss inside one heal window, elastic 8->4 shrink with loss-trajectory
# agreement, and cross-topology resume via the checkpoint meta sidecar
# (tests/test_heal.py). Runbook: OUTAGES.md. Every failure mode the
# round-5 outage demonstrated (and the mid-run one it implied), exercised
# on demand instead of by the next real outage. Same invocation locally
# and in any future CI.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m pytest -m chaos "$@"

# grafttower fleet-report smoke: a real 2-sim-host run (shared FileKVStore
# quorum, fast heartbeats), then the --fleet fold over its per-host
# events_p<k>.jsonl streams must exit 0 and print the straggler table the
# OUTAGES "which host is the problem?" runbook starts from.
FLEET_DIR="$(mktemp -d)"
FEED_DIR="$(mktemp -d)"
trap 'rm -rf "$FLEET_DIR" "$FEED_DIR"' EXIT
for i in 0 1; do
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" \
    python tests/_resilience_driver.py --fit "$FLEET_DIR/run" \
      --sim-host "$i" --sim-hosts 2 \
      --quorum-dir "$FLEET_DIR/kv" --quorum-timeout 15 \
      --obs-dir "$FLEET_DIR/obs" \
      --set obs.heartbeat_every_s=0.2 &
done
wait
JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.obs.report --fleet "$FLEET_DIR/obs" \
  | tee "$FLEET_DIR/report.txt"
grep -q "straggler table" "$FLEET_DIR/report.txt"
echo "fleet-report smoke: OK"

# graftfeed data-chaos smoke: (1) a corrupt record on a tiny CPU fit must
# quarantine + complete, and the report must fold the `data` events into
# the line the OUTAGES "data plane broke" runbook starts from; (2) a
# hung batch must crash with DataStallError inside the data-wait
# deadline, not wedge the smoke.
JAX_PLATFORMS=cpu MX_RCNN_CHAOS="data_corrupt_at=0:1" \
  python tests/_resilience_driver.py --fit "$FEED_DIR/run" \
    --obs-dir "$FEED_DIR/obs_corrupt" \
    --set data.quarantine_max_fraction=0.5
test -s "$FEED_DIR/obs_corrupt/quarantine.jsonl"
JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.obs.report "$FEED_DIR/obs_corrupt" \
  | tee "$FEED_DIR/report_corrupt.txt"
grep -q "record(s) quarantined" "$FEED_DIR/report_corrupt.txt"
if JAX_PLATFORMS=cpu MX_RCNN_CHAOS="data_hang_at=0:2 hang_s=600" \
  timeout -k 10 300 \
  python tests/_resilience_driver.py --fit "$FEED_DIR/hang" \
    --end-epoch 1 --obs-dir "$FEED_DIR/obs_hang" \
    --set data.wait_deadline_s=4.0 --set obs.stall_min_s=0.3 \
    --set obs.stall_factor=0.01 --set obs.watchdog_poll_s=0.1; then
  echo "data-hang smoke: expected DataStallError crash, run completed" >&2
  exit 1
fi
grep -q "DataStallError" "$FEED_DIR/obs_hang"/events*.jsonl
test -e "$FEED_DIR/obs_hang/flight_crash.json"
echo "data-chaos smoke: OK"
