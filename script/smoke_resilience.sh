#!/usr/bin/env bash
# graftguard + graftheal chaos gate — the fault-injection subset of tier-1
# on CPU: injected UNAVAILABLE outages, SIGTERM preemption + kill->resume
# parity, hung-bench deadline isolation, the checkpoint crash window
# (tests/test_resilience.py), and the graftheal matrix — mid-run device
# loss with heal-and-continue bit-exact parity (tree AND flat), double
# loss inside one heal window, elastic 8->4 shrink with loss-trajectory
# agreement, and cross-topology resume via the checkpoint meta sidecar
# (tests/test_heal.py). Runbook: OUTAGES.md. Every failure mode the
# round-5 outage demonstrated (and the mid-run one it implied), exercised
# on demand instead of by the next real outage. Same invocation locally
# and in any future CI.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m pytest -m chaos "$@"

# grafttower fleet-report smoke: a real 2-sim-host run (shared FileKVStore
# quorum, fast heartbeats), then the --fleet fold over its per-host
# events_p<k>.jsonl streams must exit 0 and print the straggler table the
# OUTAGES "which host is the problem?" runbook starts from.
FLEET_DIR="$(mktemp -d)"
trap 'rm -rf "$FLEET_DIR"' EXIT
for i in 0 1; do
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" \
    python tests/_resilience_driver.py --fit "$FLEET_DIR/run" \
      --sim-host "$i" --sim-hosts 2 \
      --quorum-dir "$FLEET_DIR/kv" --quorum-timeout 15 \
      --obs-dir "$FLEET_DIR/obs" \
      --set obs.heartbeat_every_s=0.2 &
done
wait
JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.obs.report --fleet "$FLEET_DIR/obs" \
  | tee "$FLEET_DIR/report.txt"
grep -q "straggler table" "$FLEET_DIR/report.txt"
echo "fleet-report smoke: OK"
