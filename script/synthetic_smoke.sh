#!/usr/bin/env bash
# Offline end-to-end smoke: train + eval + demo on the synthetic dataset.
# The only recipe runnable in this (offline, datasetless) environment —
# exercises the same code path as the real recipes.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network resnet50 --dataset synthetic --from-scratch \
  --prefix model/synthetic_smoke --end_epoch 2 --frequent 5 --tpu-mesh "${TPU_MESH:-1}" "$@"

python test.py --batch_size 4 \
  --network resnet50 --dataset synthetic --from-scratch \
  --prefix model/synthetic_smoke --epoch 2

python demo.py --network resnet50 --dataset synthetic --from-scratch \
  --prefix model/synthetic_smoke --epoch 2
