#!/usr/bin/env bash
# VGG-16 Faster R-CNN end-to-end on VOC07 trainval, eval on VOC07 test.
# Reference recipe analog: script/vgg_voc07.sh (train_end2end then test).
# Expected: ~70 mAP@0.5 (BASELINE.md row 1) after 10 epochs.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network vgg --dataset PascalVOC --image_set 2007_trainval \
  --prefix model/vgg_voc07_e2e --end_epoch 10 --lr 0.001 --lr_step 7 \
  --tpu-mesh "${TPU_MESH:-1}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network vgg --dataset PascalVOC --image_set 2007_test \
  --prefix model/vgg_voc07_e2e --epoch 10 ${COMMON_SET:-}
