#!/usr/bin/env bash
# VGG-16 Faster R-CNN 4-stage alternate optimization on VOC07 (Ren et al.).
# Reference recipe analog: script/vgg_alter_voc07.sh.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_alternate.py \
  --network vgg --dataset PascalVOC --image_set 2007_trainval \
  --prefix model/vgg_voc07_alt --rpn_epoch 8 --rcnn_epoch 8 \
  --tpu-mesh "${TPU_MESH:-1}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network vgg --dataset PascalVOC --image_set 2007_test \
  --prefix model/vgg_voc07_alt --epoch 8 ${COMMON_SET:-}
