#!/usr/bin/env bash
# ViTDet-B on COCO (BASELINE.json config 5, stretch): plain ViT backbone +
# simple feature pyramid + the FPN detection heads. Ring attention for the
# global blocks activates with a model axis: TPU_MESH=4x2 shards the token
# sequence of global-attention blocks over the 2-wide model axis.
set -euxo pipefail
cd "$(dirname "$0")/.."

python train_end2end.py \
  --network vitdet_b --dataset coco --image_set train2017 \
  --prefix model/vitdet_b_coco --end_epoch 8 --lr 0.0001 --lr_step 6 \
  --set network.proposal_topk=exact \
  --tpu-mesh "${TPU_MESH:-8}" ${COMMON_SET:-} "$@"

python test.py --batch_size 4 \
  --network vitdet_b --dataset coco --image_set val2017 \
  --prefix model/vitdet_b_coco --epoch 8 \
  --out_json results/vitdet_b_coco_dets.json ${COMMON_SET:-}
