"""Evaluate a trained detector on a dataset (reference entry point: test.py).

    python test.py --network resnet101 --dataset coco --image_set val2017 \
        --prefix model/e2e --epoch 10
"""

from __future__ import annotations

import argparse

import jax

from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache
from mx_rcnn_tpu.config import generate_config, parse_cli_overrides
from mx_rcnn_tpu.data.datasets import dataset_from_config
from mx_rcnn_tpu.data.loader import TestLoader
from mx_rcnn_tpu.evaluation.tester import Predictor, pred_eval
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models.zoo import build_model, init_params
from mx_rcnn_tpu.train.checkpoint import load_checkpoint


def parse_args():
    p = argparse.ArgumentParser(description="Test a Faster R-CNN network")
    p.add_argument("--network", default="resnet101")
    p.add_argument("--dataset", default="coco")
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/e2e")
    p.add_argument("--epoch", type=int, default=10)
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--thresh", type=float, default=1e-3)
    p.add_argument("--vis", action="store_true")
    p.add_argument("--out_json", default=None,
                   help="write COCO-format detections json")
    p.add_argument("--from-scratch", dest="from_scratch", action="store_true",
                   help="match a train_end2end.py --from-scratch checkpoint "
                        "(GroupNorm backbone)")
    p.add_argument("--set", dest="set_cfg", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="dotted config override, repeatable (must match "
                        "the training overrides that shape the graph)")
    return p.parse_args()


def main():
    enable_persistent_cache()
    args = parse_args()
    overrides = {}
    if args.root_path:
        overrides["dataset.root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset.dataset_path"] = args.dataset_path
    if args.from_scratch:
        overrides["network.norm"] = "group"
        overrides["network.freeze_at"] = 0
    overrides.update(parse_cli_overrides(args.set_cfg))
    cfg = generate_config(args.network, args.dataset, **overrides)
    image_set = args.image_set or cfg.dataset.test_image_set

    # graftscope (--set obs.enabled=true [--set obs.dir=...]): the eval
    # run gets a run_meta record and pred_eval emits the `eval` result.
    # Opened before the first device touch so graftguard backend
    # acquisition below has somewhere to emit backend_retry events.
    from mx_rcnn_tpu.obs import obs_from_config, run_meta_fields

    obs_log = obs_from_config(cfg, default_dir=f"{args.prefix}.obs")
    if cfg.resilience.backend_acquire:
        # graftguard: ride out a transient relay outage instead of dying
        # on first touch (resilience/backend.py; runbook OUTAGES.md).
        from mx_rcnn_tpu.resilience import acquire_backend

        acquire_backend(cfg.resilience, elog=obs_log)

    ds = dataset_from_config(cfg.dataset, image_set)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    template = init_params(model, cfg, jax.random.PRNGKey(0))
    params, _ = load_checkpoint(
        args.prefix, args.epoch, template={"params": template},
        means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
        num_classes=cfg.dataset.num_classes)
    predictor = Predictor(model, params, cfg)
    loader = TestLoader(roidb, cfg, batch_size=args.batch_size)
    if obs_log.enabled:
        obs_log.emit("run_meta", **run_meta_fields(
            cfg, tool="test", prefix=args.prefix, epoch=args.epoch,
            image_set=image_set))
    results = pred_eval(predictor, loader, ds, vis=args.vis,
                        thresh=args.thresh, out_json=args.out_json,
                        event_log=obs_log)
    obs_log.close()
    logger.info("evaluation: %s", results)


if __name__ == "__main__":
    main()
