"""graftguard test driver — subprocess entry + picklable sweep runners.

tests/test_resilience.py uses this module two ways:

- as a SUBPROCESS entry (``python tests/_resilience_driver.py --fit ...``)
  for the gates that need a real process boundary: the preemption exit
  code (SIGTERM → rc 75 is a process-level contract) and the
  checkpoint crash window (``--crash-save`` + chaos
  ``die_at=checkpoint_finalize`` SIGKILLs mid-save — nothing in-process
  survives that by design);
- as an IMPORT for the in-process parity gates (``tiny_config`` /
  ``run_fit``) and for the module-level functions the deadline-isolation
  tests ship to spawn children (``sweep_runner`` and friends — a spawn
  child unpickles them by qualified name, so they must live in an
  importable module, and this module's top-level imports stay
  stdlib-only to keep child startup off the jax import path).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# Script execution puts tests/ (not the repo root) on sys.path.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# picklable runners for resilience/isolate.py spawn children
# ---------------------------------------------------------------------------

def sweep_runner(label):
    """A well-behaved bench runner: one structured row, instantly."""
    return {"img_s_per_chip": 1.0, "which": label}


def sleepy_runner(label):
    """Stands in for the BENCH_r05 hung compile (without chaos wiring)."""
    time.sleep(60.0)
    return {"img_s_per_chip": 0.0, "which": label}


def error_runner(label):
    raise RuntimeError(f"relay dropped mid-measure ({label})")


# ---------------------------------------------------------------------------
# the tiny fit (in-process helper + --fit subprocess mode)
# ---------------------------------------------------------------------------

def tiny_config(flat: bool = False, obs_dir: str = "", compute: str = "f32",
                health_every: int = 0, over_extra=None):
    """The 64^2 f32 micro-config of tests/test_flatcore.py, plus
    power-of-two bbox stds: the kill->resume parity gates assert BIT
    exactness, and an emergency save round-trips bbox_pred through
    unnormalize (kernel*std) + renormalize (kernel/std) — exact for
    powers of two, not for the default 0.1/0.2. ``compute`` selects the
    graftcast policy (train/precision.py) — the bf16 parity gates run
    the exact same resume/heal machinery under compute_dtype=bf16
    (determinism holds: bf16 rounding is deterministic on one
    backend, so killed+resumed still matches uninterrupted bit for
    bit)."""
    from dataclasses import replace

    from mx_rcnn_tpu.config import generate_config

    over = {
        "train.rpn_pre_nms_top_n": 128,
        "train.rpn_post_nms_top_n": 32,
        "train.batch_rois": 16,
        "train.max_gt_boxes": 4,
        "train.batch_images": 1,
        "train.flip": False,
        "network.anchor_scales": (2, 4),
        "image.pad_shape": (64, 64),
        "image.scales": ((64, 64),),
    }
    if obs_dir:
        over["obs.enabled"] = True
        over["obs.dir"] = obs_dir
        # graftprof's per-bucket AOT cost capture re-traces the step once
        # per shape bucket — pure compile-time, but these gates are about
        # resilience, not attribution; keep them inside the tier-1 budget.
        over["obs.cost_analysis"] = False
        # graftpulse: in-graph health at every Nth dispatch (0 = off).
        # The nan_at_step gates run every=1 so the tripwire sees the
        # poisoned dispatch the moment it lands.
        over["obs.health_every"] = health_every
    if over_extra:
        # graftquorum gates thread resilience.quorum_* / elastic_mode
        # overrides through here (dotted config keys).
        over.update(over_extra)
    cfg = generate_config("resnet50", "synthetic", **over)
    return cfg.with_updates(
        train=replace(cfg.train, flat_params=flat, compute_dtype=compute,
                      bbox_stds=(0.5, 0.5, 0.25, 0.25)))


def run_fit(prefix: str, end_epoch: int = 2, resume=False,
            flat: bool = False, obs_dir: str = "", mesh: str = "1",
            num_images: int = 3, epoch_metrics=None, compute: str = "f32",
            health_every: int = 0, over_extra=None):
    """num_images x 64^2, seed 0 — returns the final host params.
    Deterministic end to end, so an interrupted+resumed (or graftheal-ed)
    run must match an uninterrupted one bit for bit. ``mesh`` sizes the
    data axis (the heal shrink gates run "8" on the virtual CPU mesh);
    ``epoch_metrics`` (a list) collects ``(epoch, bag.get())`` per epoch —
    the loss trajectory the elastic gates compare."""
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    ds = SyntheticDataset("train", num_images=num_images, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    cb = None
    if epoch_metrics is not None:
        def cb(epoch, state, bag):
            epoch_metrics.append((epoch, bag.get()))
    return fit_detector(tiny_config(flat, obs_dir, compute, health_every,
                                    over_extra=over_extra),
                        ds.gt_roidb(),
                        prefix=prefix, end_epoch=end_epoch, frequent=1000,
                        seed=0, mesh_spec=mesh, resume=resume,
                        epoch_callback=cb)


def _crash_save(prefix: str, scale: float = 1.0):
    """One sync checkpoint save of a known tiny tree (``scale`` makes
    successive saves distinguishable). With chaos ``die_at=
    checkpoint_finalize`` / ``checkpoint_swap`` armed the process
    SIGKILLs inside that crash window; unarmed it publishes."""
    import numpy as np

    from mx_rcnn_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(prefix, 1,
                    {"w": scale * np.arange(6, dtype=np.float32).reshape(2, 3)})


def _coerce(raw: str):
    """Literal coercion for --set values: int, float, bool, else str."""
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    low = raw.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    return raw


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--fit", metavar="PREFIX",
                   help="run the tiny training run under PREFIX")
    p.add_argument("--end-epoch", type=int, default=2)
    p.add_argument("--resume", nargs="?", const=True, default=False,
                   choices=[True, "auto"], metavar="auto")
    p.add_argument("--flat", action="store_true",
                   help="train.flat_params=true mode")
    p.add_argument("--obs-dir", default="")
    p.add_argument("--mesh", default="1", help="mesh spec (data[xmodel])")
    p.add_argument("--num-images", type=int, default=3)
    p.add_argument("--compute", default="f32", choices=["f32", "bf16"],
                   help="graftcast train.compute_dtype policy")
    p.add_argument("--crash-save", metavar="PREFIX",
                   help="one sync checkpoint save (the crash-window probe)")
    p.add_argument("--scale", type=float, default=1.0,
                   help="scale factor on the --crash-save tree")
    # graftquorum simulated-host mode: N of these processes, each a full
    # replicated computation, coordinate through a shared FileKVStore as
    # if they were N pod hosts (parallel/distributed.py sim contract).
    p.add_argument("--sim-host", type=int, default=None, metavar="I",
                   help="stand in for host I of a simulated fleet")
    p.add_argument("--sim-hosts", type=int, default=None, metavar="N",
                   help="size of the simulated fleet")
    p.add_argument("--quorum-dir", default="",
                   help="resilience.quorum_store_dir (shared FileKVStore)")
    p.add_argument("--quorum-timeout", type=float, default=0.0,
                   help="resilience.quorum_timeout_s override (0 = keep)")
    p.add_argument("--elastic-mode", default="",
                   choices=["", "shrink", "grow", "rescale"],
                   help="resilience.elastic_mode override")
    # grafttower gates thread heartbeat/fleet knobs through here without
    # growing a flag per knob: repeatable dotted config overrides with
    # literal coercion (int -> float -> bool -> str).
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="extra dotted config override (repeatable), e.g. "
                        "--set obs.heartbeat_every_s=0.2")
    args = p.parse_args(argv)

    if args.sim_host is not None or args.sim_hosts is not None:
        if args.sim_host is None or args.sim_hosts is None:
            p.error("--sim-host and --sim-hosts go together")
        # Coordination identity only — jax itself stays single-process
        # (env must land before mx_rcnn_tpu reads it at call time).
        os.environ["MXRCNN_SIM_PROCESS_ID"] = str(args.sim_host)
        os.environ["MXRCNN_SIM_NUM_PROCESSES"] = str(args.sim_hosts)

    if args.mesh not in ("", "1", "1x1"):
        # Multi-device mesh in a subprocess: the virtual CPU devices must
        # be requested BEFORE jax initializes (same dance as conftest.py).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()  # share tests/.jax_cache with the suite

    if args.crash_save:
        _crash_save(args.crash_save, scale=args.scale)
        return 0
    if args.fit:
        over_extra = {}
        if args.quorum_dir:
            over_extra["resilience.quorum_store_dir"] = args.quorum_dir
        if args.quorum_timeout:
            over_extra["resilience.quorum_timeout_s"] = args.quorum_timeout
        if args.elastic_mode:
            over_extra["resilience.elastic_mode"] = args.elastic_mode
        for pair in args.overrides:
            key, sep, raw = pair.partition("=")
            if not sep:
                p.error(f"--set expects KEY=VALUE, got {pair!r}")
            over_extra[key] = _coerce(raw)
        run_fit(args.fit, end_epoch=args.end_epoch, resume=args.resume,
                flat=args.flat, obs_dir=args.obs_dir, mesh=args.mesh,
                num_images=args.num_images, compute=args.compute,
                over_extra=over_extra or None)
        return 0
    p.error("one of --fit / --crash-save is required")


if __name__ == "__main__":
    sys.exit(main())
