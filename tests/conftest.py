"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per SURVEY.md §5 the sharding
tests run on host-simulated devices. Must set env BEFORE jax import.
"""

import os

# Unconditional: the shell exports JAX_PLATFORMS=axon (real TPU), which would
# make the suite run single-device on hardware and never create the 8-device
# mesh. Tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU tests deterministic and fast.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
