"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per SURVEY.md §5 the sharding
tests run on host-simulated devices.

Gotcha (verified): /root/.axon_site/sitecustomize.py pre-imports jax at
interpreter startup with JAX_PLATFORMS=axon, so setting the env var here is
too late for jax's config snapshot — but XLA_FLAGS is read at backend-init
time (still ahead of us) and the platform is switchable via
jax.config.update after import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall time is dominated by XLA
# re-compiles of the same jitted steps across test processes/runs; cache
# them on disk (tests/.jax_cache, gitignored) so repeat runs pay tracing
# only. Threshold 0.1s keeps only trivial kernels out of the cache.
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache()

import numpy as np
import pytest

#: below this much free space in the tmp dir, checkpoint-writing fixtures
#: skip loudly instead of dying mid-write with a phantom FileNotFoundError
#: (PR 12's notes: a full /tmp surfaces as missing .npz shards, not ENOSPC)
_TMP_FREE_FLOOR_BYTES = 512 * 1024 * 1024


def _tmp_free_bytes() -> int:
    import shutil
    import tempfile

    try:
        return shutil.disk_usage(tempfile.gettempdir()).free
    except OSError:
        return _TMP_FREE_FLOOR_BYTES  # unknowable — don't block the run


def _require_tmp_space(what: str):
    free = _tmp_free_bytes()
    if free < _TMP_FREE_FLOOR_BYTES:
        pytest.skip(
            f"/tmp has only {free // (1024 * 1024)} MiB free "
            f"(< {_TMP_FREE_FLOOR_BYTES // (1024 * 1024)} MiB floor) — "
            f"{what} writes checkpoints there and would fail with "
            "misleading FileNotFoundErrors; free space and re-run")


@pytest.fixture(scope="session", autouse=True)
def _prune_run_tmp(tmp_path_factory):
    """Session finalizer: delete THIS run's pytest tmp tree (checkpoint
    dirs from the fit baselines and resilience tests are the bulk of it)
    so repeated runs stop accumulating toward /tmp exhaustion. pytest's
    own keep-3-runs retention never fires when a run is killed mid-way;
    this always does."""
    yield
    import shutil

    base = tmp_path_factory.getbasetemp()
    shutil.rmtree(base, ignore_errors=True)


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def _uninterrupted_fit(tmp_path_factory, name, **kw):
    """One chaos-clean tiny fit (tests/_resilience_driver.py::run_fit)
    whose final params serve as a shared bit-exactness baseline. Armed
    chaos must not leak into it."""
    import _resilience_driver as driver
    from mx_rcnn_tpu.resilience import chaos

    _require_tmp_space(f"the {name} baseline fit")
    old = os.environ.pop(chaos.ENV_VAR, None)
    chaos.reset()
    try:
        prefix = str(tmp_path_factory.mktemp(name) / "u")
        return driver.run_fit(prefix, **kw)
    finally:
        if old is not None:
            os.environ[chaos.ENV_VAR] = old
        chaos.reset()


@pytest.fixture(scope="session")
def bf16_flat_baseline(tmp_path_factory):
    """Uninterrupted flat + compute_dtype=bf16 tiny fit params — the ONE
    graftcast parity reference shared by the kill→resume gate
    (tests/test_resilience.py), the heal-carry gate (tests/test_heal.py)
    and the graftpulse nan→resume gate (tests/test_health.py). Session
    scope: all compare against the bit-identical deterministic run, so a
    single baseline fit pays for every consumer (tier-1 budget)."""
    return _uninterrupted_fit(tmp_path_factory, "bf16_base",
                              flat=True, compute="bf16")


@pytest.fixture(scope="session")
def tree_f32_baseline(tmp_path_factory):
    """Uninterrupted tree-mode f32 tiny fit params — shared by the
    SIGTERM kill→resume parity gate (tests/test_resilience.py) and the
    graftpulse nan→resume gate (tests/test_health.py)."""
    return _uninterrupted_fit(tmp_path_factory, "tree_base", flat=False)


@pytest.fixture(scope="session")
def flat_f32_baseline(tmp_path_factory):
    """Uninterrupted flat-mode f32 tiny fit params — same sharing
    contract as tree_f32_baseline."""
    return _uninterrupted_fit(tmp_path_factory, "flat_base", flat=True)
