"""graftlint rule fixtures: one must-flag and one near-miss per rule.

These drive ``lint_source`` directly (no files, no subprocess) so each
rule's positive/negative contract is pinned independently of the live
tree's state. The live-tree gate is tests/test_lint_clean.py.
"""

import ast
import textwrap

import pytest

from mx_rcnn_tpu.analysis import Settings, lint_source, lint_sources
from mx_rcnn_tpu.analysis.rules import ALL_RULES


def lint(src, settings=None):
    return lint_source(textwrap.dedent(src), "snippet.py",
                       settings or Settings(), ALL_RULES)


def lint_files(files, settings=None):
    """Multi-file mini-program: {rel_path: snippet} — reachability closes
    over ALL files before rules run (graftsight's whole-program path)."""
    return lint_sources(
        {path: textwrap.dedent(src) for path, src in files.items()},
        settings or Settings(), ALL_RULES)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# host-sync-in-jit
# ---------------------------------------------------------------------------

def test_host_sync_flags_item_inside_jit():
    findings = lint("""
        import jax

        @jax.jit
        def f(params, x):
            return x.sum().item()
    """)
    assert "host-sync-in-jit" in rules_of(findings)


def test_host_sync_flags_float_of_traced_value():
    findings = lint("""
        import jax

        def f(x):
            y = x * 2
            return float(y)

        g = jax.jit(f)
    """)
    assert "host-sync-in-jit" in rules_of(findings)


def test_host_sync_flags_np_asarray_and_device_get_in_traced_code():
    findings = lint("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = np.asarray(x)
            b = jax.device_get(x)
            return a, b
    """)
    assert sum(f.rule == "host-sync-in-jit" for f in findings) == 2


def test_host_sync_near_miss_static_shape_idioms():
    findings = lint("""
        import jax

        @jax.jit
        def f(x):
            n = int(x.shape[0])      # static under jit — fine
            m = int(len(x))          # ditto
            return x.reshape(n, m, -1)
    """)
    assert "host-sync-in-jit" not in rules_of(findings)


def test_host_sync_near_miss_outside_jit_and_static_float():
    findings = lint("""
        import jax

        def host_metric(arr):
            return arr.sum().item()  # host code — fine

        def make(cfg):
            thresh = float(cfg.test.nms_thresh)  # static config — fine

            @jax.jit
            def f(x):
                return x * thresh

            return f
    """)
    assert "host-sync-in-jit" not in rules_of(findings)


# ---------------------------------------------------------------------------
# data-dependent-shape
# ---------------------------------------------------------------------------

def test_shape_flags_nonzero_without_size():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.nonzero(x > 0)
    """)
    assert "data-dependent-shape" in rules_of(findings)


def test_shape_flags_boolean_mask_indexing():
    findings = lint("""
        import jax

        @jax.jit
        def f(x):
            mask = x > 0
            return x[mask] + x[x > 1]
    """)
    assert sum(f.rule == "data-dependent-shape" for f in findings) == 2


def test_shape_mask_tracking_is_position_sensitive():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            mask = x > 0
            y = x[mask]              # mask IS a compare here -> flag
            mask = jnp.argmax(x)
            z = x[mask]              # integer index now -> no flag
            return y, z
    """)
    hits = [f for f in findings if f.rule == "data-dependent-shape"]
    assert len(hits) == 1 and hits[0].text.startswith("y =")


def test_shape_near_miss_sized_nonzero_and_host_code():
    findings = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            idx = jnp.nonzero(x > 0, size=128, fill_value=-1)
            sel = jnp.where(x > 0, x, 0.0)  # 3-arg select — fine
            return idx, sel

        def host(arr):
            return np.nonzero(arr)  # host code — fine
    """)
    assert "data-dependent-shape" not in rules_of(findings)


# ---------------------------------------------------------------------------
# missing-donation
# ---------------------------------------------------------------------------

def test_donation_flags_state_step_without_donate():
    findings = lint("""
        import jax

        def make(model):
            def step(state, batch, rng):
                return state

            return jax.jit(step)
    """)
    assert "missing-donation" in rules_of(findings)


def test_donation_flags_decorator_form():
    findings = lint("""
        import jax

        @jax.jit
        def step(train_state, batch):
            return train_state
    """)
    assert "missing-donation" in rules_of(findings)


def test_donation_near_miss_partial_call_form():
    findings = lint("""
        import jax
        from functools import partial

        def make():
            def step(state, batch):
                return state

            return partial(jax.jit, donate_argnums=(0,))(step)
    """)
    assert "missing-donation" not in rules_of(findings)


def test_donation_near_miss_donated_or_stateless():
    findings = lint("""
        import jax
        from functools import partial

        def make():
            def step(state, batch, rng):
                return state

            def predict(params, image):
                return params, image

            a = jax.jit(step, donate_argnums=(0,))
            b = jax.jit(step, donate_argnums=(0,) if True else ())
            c = jax.jit(predict)  # params-first inference — no convention
            return a, b, c

        @partial(jax.jit, donate_argnums=(0,))
        def step2(state, batch):
            return state
    """)
    assert "missing-donation" not in rules_of(findings)


# ---------------------------------------------------------------------------
# prng-key-reuse
# ---------------------------------------------------------------------------

def test_prng_flags_double_consumption():
    findings = lint("""
        import jax

        def sample(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))
            return a + b
    """)
    assert "prng-key-reuse" in rules_of(findings)


def test_prng_flags_use_after_split():
    findings = lint("""
        import jax

        def sample(key):
            k1, k2 = jax.random.split(key)
            return jax.random.uniform(key)  # key retired by split
    """)
    assert "prng-key-reuse" in rules_of(findings)


def test_prng_flags_loop_carried_reuse():
    findings = lint("""
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.uniform(key))
            return out
    """)
    assert "prng-key-reuse" in rules_of(findings)


def test_prng_near_miss_split_and_carried_key():
    findings = lint("""
        import jax

        def sample(key, n):
            key, k1, k2 = jax.random.split(key, 3)
            a = jax.random.uniform(k1)
            b = jax.random.normal(k2)
            out = []
            for i in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.uniform(sub))
            keys = jax.random.split(key, n)
            c = [jax.random.uniform(keys[i]) for i in range(n)]
            return a, b, out, c
    """)
    assert "prng-key-reuse" not in rules_of(findings)


def test_prng_loop_reuse_reported_once_per_site():
    findings = lint("""
        import jax

        def sample(key, n):
            for i in range(n):
                a = jax.random.normal(key)
                b = jax.random.normal(key)
            return a, b
    """)
    hits = [f for f in findings if f.rule == "prng-key-reuse"]
    # one per defective call site, not duplicated by the two-pass loop walk
    assert len(hits) == len({(f.line, f.col) for f in hits}) == 2


def test_prng_near_miss_exclusive_branches():
    findings = lint("""
        import jax

        def sample(key, flip):
            if flip:
                return jax.random.uniform(key)
            else:
                return jax.random.normal(key)
    """)
    assert "prng-key-reuse" not in rules_of(findings)


def test_prng_near_miss_key_rebound_in_both_branches():
    findings = lint("""
        import jax

        def sample(key, c, bank):
            x = jax.random.normal(key)
            if c:
                key = bank.fresh(1)
            else:
                key = bank.fresh(2)
            return x + jax.random.normal(key)  # fresh on every path
    """)
    assert "prng-key-reuse" not in rules_of(findings)


def test_prng_near_miss_try_except_alternate_outcomes():
    findings = lint("""
        import jax

        def sample(key):
            try:
                return jax.random.uniform(key)
            except ValueError:
                return jax.random.normal(key)
    """)
    assert "prng-key-reuse" not in rules_of(findings)


def test_prng_flags_reuse_after_if_test_consumption():
    findings = lint("""
        import jax

        def sample(key):
            if jax.random.bernoulli(key):
                return jax.random.uniform(key)
            return 0.0
    """)
    hits = [f for f in findings if f.rule == "prng-key-reuse"]
    # the reuse site is the BODY call, not the test
    assert len(hits) == 1 and "uniform" in hits[0].text


def test_prng_flags_consumption_in_while_header():
    findings = lint("""
        import jax

        def sample(key):
            a = jax.random.uniform(key)
            while jax.random.bernoulli(key):
                pass
            return a
    """)
    assert "prng-key-reuse" in rules_of(findings)


# ---------------------------------------------------------------------------
# cfg-contract
# ---------------------------------------------------------------------------

def test_cfg_contract_flags_misspelled_field():
    # The acceptance fixture: rpn_batchsize vs the real rpn_batch_size.
    findings = lint("""
        def assign(cfg):
            return cfg.train.rpn_batchsize
    """)
    hits = [f for f in findings if f.rule == "cfg-contract"]
    assert hits and "rpn_batchsize" in hits[0].message


def test_cfg_contract_flags_unknown_section_and_alias_typo():
    findings = lint("""
        def f(cfg):
            a = cfg.trian.lr          # section typo
            net = cfg.network
            b = net.deepth            # alias field typo
            return a, b
    """)
    assert sum(f.rule == "cfg-contract" for f in findings) == 2


def test_cfg_contract_flags_annotated_param():
    findings = lint("""
        from mx_rcnn_tpu.config import NetworkConfig

        def f(net: NetworkConfig):
            return net.rio_pool_size  # typo of roi_pool_size
    """)
    assert "cfg-contract" in rules_of(findings)


def test_cfg_contract_near_miss_valid_chains():
    findings = lint("""
        def f(cfg):
            a = cfg.train.rpn_batch_size
            b = cfg.network.num_anchors      # property
            c = cfg.with_updates(seed=1)     # method
            d = cfg.train.bbox_stds[0]
            net = cfg.network
            e = net.roi_pool_size
            f_ = cfg.image.pad_shape
            return a, b, c, d, e, f_
    """)
    assert "cfg-contract" not in rules_of(findings)


def test_cfg_contract_shadowed_cfg_binding_is_exempt():
    findings = lint("""
        import json

        def f(path):
            cfg = json.load(open(path))   # visibly NOT the Config tree
            return cfg.get("train")

        def g():
            cfg = {"train": 1}
            return cfg.items()
    """)
    assert "cfg-contract" not in rules_of(findings)


def test_cfg_contract_ignores_unrelated_names():
    findings = lint("""
        def f(other):
            return other.train.rpn_batchsize  # not a cfg root
    """)
    assert "cfg-contract" not in rules_of(findings)


# ---------------------------------------------------------------------------
# broad-except
# ---------------------------------------------------------------------------

def test_broad_except_flags_handler_around_work():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """)
    assert "broad-except" in rules_of(findings)


def test_broad_except_flags_bare_except():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except:
                return None
    """)
    assert "broad-except" in rules_of(findings)


def test_broad_except_near_miss_import_probe_and_named_types():
    findings = lint("""
        try:
            import cv2
            _HAS_CV2 = True
        except Exception:
            _HAS_CV2 = False

        def load(path):
            try:
                return open(path).read()
            except (OSError, ValueError):
                return None
    """)
    assert "broad-except" not in rules_of(findings)


# ---------------------------------------------------------------------------
# engine mechanics: suppressions, baseline, syntax errors
# ---------------------------------------------------------------------------

def test_inline_suppression_silences_only_named_rule():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except Exception:  # graftlint: disable=broad-except — forwarded
                return None
    """)
    assert "broad-except" not in rules_of(findings)


def test_inline_suppression_other_rule_does_not_silence():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except Exception:  # graftlint: disable=prng-key-reuse
                return None
    """)
    assert "broad-except" in rules_of(findings)


def test_disable_marker_inside_string_literal_does_not_suppress():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except Exception: doc = "# graftlint: disable=broad-except"
    """)
    assert "broad-except" in rules_of(findings)


def test_overlapping_paths_lint_each_file_once(tmp_path):
    from mx_rcnn_tpu.analysis.engine import iter_python_files

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text("x = 1\n")
    files = list(iter_python_files(["pkg", "pkg/m.py"], str(tmp_path)))
    assert len(files) == 1


def test_baseline_matcher_absorbs_and_reports_stale():
    from mx_rcnn_tpu.analysis import baseline as bl
    from mx_rcnn_tpu.analysis.engine import Finding

    f = Finding(path="a.py", rule="broad-except", line=3, col=1,
                message="m", text="except Exception:")
    matcher = bl.Matcher([
        {"path": "a.py", "rule": "broad-except",
         "text": "except Exception:", "count": 1},
        {"path": "gone.py", "rule": "broad-except", "text": "x", "count": 1},
    ])
    assert matcher.consume(f)
    assert not matcher.consume(f)  # budget exhausted
    assert ("gone.py", "broad-except", "x") in matcher.unused()


def test_baseline_matches_on_text_not_line():
    from mx_rcnn_tpu.analysis import baseline as bl
    from mx_rcnn_tpu.analysis.engine import Finding

    matcher = bl.Matcher([{"path": "a.py", "rule": "broad-except",
                           "text": "except Exception:", "count": 1}])
    shifted = Finding(path="a.py", rule="broad-except", line=99, col=1,
                      message="m", text="except Exception:")
    assert matcher.consume(shifted)


def test_syntax_error_reports_as_finding():
    findings = lint("def broken(:\n")
    assert rules_of(findings) == {"syntax"}


def test_disabled_rule_is_skipped():
    findings = lint("""
        def load(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """, settings=Settings(disable=("broad-except",)))
    assert findings == []


@pytest.fixture
def mini_repo(tmp_path):
    """A throwaway lint root: clean a.py, violating b.py, baseline for b."""
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.graftlint]
        paths = ["a.py", "b.py"]
        baseline = "bl.json"
    """))
    (tmp_path / "a.py").write_text("def ok():\n    return 1\n")
    (tmp_path / "b.py").write_text(textwrap.dedent("""
        def load(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """))
    import mx_rcnn_tpu.analysis.cli as cli

    assert cli.main(["--root", str(tmp_path), "--write-baseline"]) == 0
    return tmp_path


def test_cli_subset_run_does_not_report_out_of_scope_stale(mini_repo, capsys):
    import mx_rcnn_tpu.analysis.cli as cli

    # b.py's baseline entry is out of scope for a subset run over a.py
    assert cli.main(["--root", str(mini_repo), "a.py"]) == 0
    assert "stale" not in capsys.readouterr().out


def test_cli_disabled_rule_baseline_entries_are_not_stale(mini_repo, capsys):
    import mx_rcnn_tpu.analysis.cli as cli

    # b.py's broad-except entry is unexercised when the rule is off —
    # that is not staleness, and must not fail the gate
    assert cli.main(["--root", str(mini_repo),
                     "--disable", "broad-except"]) == 0
    assert "stale" not in capsys.readouterr().out


def test_cli_subset_write_baseline_keeps_out_of_scope_entries(mini_repo):
    import json

    import mx_rcnn_tpu.analysis.cli as cli

    assert cli.main(["--root", str(mini_repo), "a.py",
                     "--write-baseline"]) == 0
    data = json.loads((mini_repo / "bl.json").read_text())
    assert [e["path"] for e in data["suppressions"]] == ["b.py"]
    # and the full run still passes against the merged baseline
    assert cli.main(["--root", str(mini_repo)]) == 0


def test_transitive_trace_closure_reaches_helpers():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        def helper(x):
            return jnp.nonzero(x)  # traced via caller

        def caller(x):
            return helper(x)

        f = jax.jit(caller)
    """)
    assert "data-dependent-shape" in rules_of(findings)


def test_pallas_kernel_via_partial_is_traced():
    findings = lint("""
        from functools import partial
        import jax.experimental.pallas as pl
        import numpy as np

        def kernel(x_ref, o_ref, scale):
            o_ref[...] = np.asarray(x_ref[...]) * scale

        def run(x):
            return pl.pallas_call(partial(kernel, scale=2.0),
                                  out_shape=None)(x)
    """)
    assert "host-sync-in-jit" in rules_of(findings)


# ---------------------------------------------------------------------------
# obs-event-schema
# ---------------------------------------------------------------------------

def test_obs_schema_flags_unknown_type():
    findings = lint("""
        def run(obs_log):
            obs_log.emit("stepp", step_ms=1.0)
    """)
    assert "obs-event-schema" in rules_of(findings)
    msg = next(f for f in findings if f.rule == "obs-event-schema").message
    assert "unknown event type 'stepp'" in msg


def test_obs_schema_flags_non_literal_type_key():
    findings = lint("""
        def run(obs, kind):
            obs.emit(kind, step_ms=1.0)
            obs.emit()
    """)
    assert sum(f.rule == "obs-event-schema" for f in findings) == 2


def test_obs_schema_near_miss_known_literals_and_foreign_emit():
    findings = lint("""
        def run(event_log, handler, record, signal):
            event_log.emit("step", step_ms=1.0)
            event_log.emit("run_meta", config_digest="abc")
            self_obs = event_log
            self_obs.emit("stall", waited_s=2.0)
            handler.emit(record)      # logging.Handler — out of scope
            signal.emit("anything")   # Qt-style signal — out of scope
    """)
    assert "obs-event-schema" not in rules_of(findings)


# ---------------------------------------------------------------------------
# flat-state-access
# ---------------------------------------------------------------------------

def test_flat_state_flags_opt_state_subscript_in_jit():
    findings = lint("""
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, grads):
            trace = state.opt_state[1][0]   # optax chain-position poking
            return trace
    """)
    assert sum(f.rule == "flat-state-access" for f in findings) == 1


def test_flat_state_flags_bare_name_in_jitted_closure():
    findings = lint("""
        import jax

        def make(opt_state):
            def inner(x):
                return x + opt_state[0].count

            return jax.jit(inner)
    """)
    assert "flat-state-access" in rules_of(findings)


def test_flat_state_near_miss_host_side_and_tree_map():
    findings = lint("""
        import functools

        import jax

        def restore(opt_state):
            return opt_state[0]        # host-side conversion — fine

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, grads):
            # whole-tree access is layout-agnostic — fine
            return jax.tree.map(lambda t: t * 0.9, state.opt_state)
    """)
    assert "flat-state-access" not in rules_of(findings)


def test_flat_state_near_miss_template_names():
    findings = lint("""
        import jax

        @jax.jit
        def f(opt_state_template):
            return opt_state_template["params"]
    """)
    assert "flat-state-access" not in rules_of(findings)


# ---------------------------------------------------------------------------
# unbounded-retry
# ---------------------------------------------------------------------------

def test_retry_flags_the_r5_watcher_shape():
    """The literal TPU_OUTAGE_r5.log anti-pattern: while True, swallow,
    sleep a constant — no deadline, no backoff."""
    findings = lint("""
        import time

        import jax

        def wait_for_tpu():
            while True:
                try:
                    return jax.devices()
                except RuntimeError:
                    time.sleep(540)  # fixed 9-minute cadence
    """)
    assert sum(f.rule == "unbounded-retry" for f in findings) == 1


def test_retry_flags_itertools_count_disguise():
    findings = lint("""
        import itertools
        import time

        def probe(connect):
            for attempt in itertools.count():
                try:
                    return connect()
                except ConnectionError:
                    time.sleep(5)
    """)
    assert "unbounded-retry" in rules_of(findings)


def test_retry_flags_constant_arithmetic_cadence():
    """sleep(9 * 60) is the same fixed cadence as sleep(540) — constant
    arithmetic must not read as per-iteration computation (backoff)."""
    findings = lint("""
        import time

        import jax

        def wait_for_tpu():
            while True:
                try:
                    return jax.devices()
                except RuntimeError:
                    time.sleep(9 * 60)
    """)
    assert sum(f.rule == "unbounded-retry" for f in findings) == 1


def test_retry_flags_fixed_cadence_from_untouched_name():
    """sleep(PAUSE) where the loop never reassigns PAUSE is still a
    fixed cadence, not backoff."""
    findings = lint("""
        import time

        PAUSE = 9 * 60

        def watch(probe):
            while True:
                try:
                    return probe()
                except RuntimeError:
                    time.sleep(PAUSE)
    """)
    assert "unbounded-retry" in rules_of(findings)


def test_retry_near_miss_bounded_for_and_backoff_and_deadline():
    findings = lint("""
        import time

        def bounded(probe):
            for _ in range(5):            # finite attempts
                try:
                    return probe()
                except RuntimeError:
                    time.sleep(1.0)

        def backoff(probe):
            delay = 1.0
            while True:
                try:
                    return probe()
                except RuntimeError:
                    time.sleep(delay)
                    delay = min(delay * 2, 300.0)   # backoff evidence

        def deadlined(probe, deadline_s):
            start = time.monotonic()
            while True:
                try:
                    return probe()
                except RuntimeError:
                    if time.monotonic() - start > deadline_s:
                        raise
                    time.sleep(2.0)
    """)
    assert "unbounded-retry" not in rules_of(findings)


def test_retry_near_miss_poll_loop_without_handler():
    """A sleep-poll loop with no except handler is a wait loop, not a
    retry loop — out of scope."""
    findings = lint("""
        import time

        def wait_until(ready):
            while not ready():
                time.sleep(0.5)
    """)
    assert "unbounded-retry" not in rules_of(findings)


# ---------------------------------------------------------------------------
# chaos-site-name
# ---------------------------------------------------------------------------

def test_chaos_site_flags_typoed_site():
    findings = lint("""
        from mx_rcnn_tpu.resilience import chaos

        def publish(tmp, final):
            chaos.site("checkpoint_finalze")   # typo: never fires
    """)
    assert "chaos-site-name" in rules_of(findings)
    msg = next(f for f in findings if f.rule == "chaos-site-name").message
    assert "unregistered chaos site 'checkpoint_finalze'" in msg


def test_chaos_site_flags_non_literal_and_missing_name():
    findings = lint("""
        def loop(chaos_spec, where):
            chaos_spec.fire(where, step=3)
            chaos_spec.maybe_die()
    """)
    assert sum(f.rule == "chaos-site-name" for f in findings) == 2


def test_chaos_site_near_miss_registered_and_foreign_receivers():
    findings = lint("""
        from mx_rcnn_tpu.resilience import chaos

        def run(chaos_spec, laser, evt):
            chaos.site("checkpoint_finalize")
            chaos.site("backend_reacquire", devices=[1, 2])
            chaos_spec.fire("train_dispatch", step=3)
            chaos_spec.maybe_die("checkpoint_swap")
            laser.fire(evt)          # foreign receiver — out of scope
            laser.site("anywhere")   # ditto
    """)
    assert "chaos-site-name" not in rules_of(findings)


# ---------------------------------------------------------------------------
# time-in-jit
# ---------------------------------------------------------------------------

def test_time_in_jit_flags_clock_in_jitted_function():
    findings = lint("""
        import time

        import jax

        @jax.jit
        def step(state, batch):
            t0 = time.perf_counter()   # trace-time constant!
            return state, t0
    """)
    assert "time-in-jit" in rules_of(findings)
    msg = next(f for f in findings if f.rule == "time-in-jit").message
    assert "trace" in msg


def test_time_in_jit_flags_from_import_in_traced_closure():
    """from-time imports (aliased too) and same-module reachability:
    the helper is traced because the jitted root calls it."""
    findings = lint("""
        from time import monotonic as clock

        import jax

        def _timed_part(x):
            return x * clock()

        def step(x):
            return _timed_part(x) + 1

        run = jax.jit(step)
    """)
    assert "time-in-jit" in rules_of(findings)


def test_time_in_jit_near_miss_host_side_timing():
    """Host-side clock reads — the StepTimer/bench shape, including in a
    module that jits OTHER functions — stay legal."""
    findings = lint("""
        import time

        import jax

        def bench(fn, x):
            compiled = jax.jit(lambda v: v * 2)
            t0 = time.perf_counter()
            compiled(x)
            return time.perf_counter() - t0

        def wall():
            return time.time()
    """)
    assert "time-in-jit" not in rules_of(findings)


def test_time_in_jit_near_miss_unrelated_names():
    """A non-time `time` attribute or a local function named like a
    clock must not flag."""
    findings = lint("""
        import jax

        @jax.jit
        def step(sim, x):
            return sim.time() + x.sum()

        def perf_counter():
            return 7
    """)
    assert "time-in-jit" not in rules_of(findings)


# ---------------------------------------------------------------------------
# dtype-cast-in-jit
# ---------------------------------------------------------------------------

def lint_model(src):
    """Lint a snippet AS model code, driven by a jitted entry in a
    DIFFERENT module — the rule fires only on jit-reachable model code
    now, so the fixture exercises graftsight's cross-module closure
    (auto-generating one driver call per top-level def/class)."""
    import textwrap as _tw

    src = _tw.dedent(src)
    calls = []
    for item in ast.parse(src).body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            calls.append(f"    snippet.{item.name}(x, x)")
        elif isinstance(item, ast.ClassDef):
            calls.append(f"    snippet.{item.name}()(x)")
    driver = "\n".join([
        "import jax",
        "from mx_rcnn_tpu.models import snippet",
        "",
        "def _drive(x):",
    ] + (calls or ["    pass"]) + [
        "",
        "run = jax.jit(_drive)",
    ])
    findings = lint_files({
        "mx_rcnn_tpu/models/snippet.py": src,
        "mx_rcnn_tpu/train/driver.py": driver,
    })
    return [f for f in findings
            if f.path == "mx_rcnn_tpu/models/snippet.py"]


def test_dtype_cast_flags_astype_float_literal_in_model_code():
    findings = lint_model("""
        import jax.numpy as jnp

        def forward(params, x):
            logits = x @ params["w"]
            return logits.astype(jnp.float32)
    """)
    assert sum(f.rule == "dtype-cast-in-jit" for f in findings) == 1


def test_dtype_cast_flags_asarray_of_flowing_data_and_string_spelling():
    findings = lint_model("""
        import jax.numpy as jnp

        def decode(deltas, stds):
            d = jnp.asarray(deltas, jnp.bfloat16)      # flowing data
            s = stds.astype("float32")                 # string spelling
            return d * s
    """)
    assert sum(f.rule == "dtype-cast-in-jit" for f in findings) == 2


def test_dtype_cast_flags_keyword_astype_spelling():
    """x.astype(dtype=jnp.float32) is the same policy bypass as the
    positional spelling — the rule must not be evadable by keyword."""
    findings = lint_model("""
        import jax.numpy as jnp

        def forward(params, x):
            return x.astype(dtype=jnp.float32)
    """)
    assert sum(f.rule == "dtype-cast-in-jit" for f in findings) == 1


def test_dtype_cast_near_miss_policy_dtype_int_and_constants():
    """The sanctioned spellings: the module's policy dtype, integer
    dtypes, and CONSTANT construction in an explicit dtype."""
    findings = lint_model("""
        import jax.numpy as jnp

        class Head:
            def __call__(self, x):
                y = x.astype(self.dtype)               # policy-routed
                idx = y.astype(jnp.int32)              # not a float cast
                rois = jnp.asarray([[0.0, 0.0, 31.0, 31.0]], jnp.float32)
                zeros = jnp.zeros((4, 4), jnp.float32)  # declaration
                return y, idx, rois, zeros
    """)
    assert "dtype-cast-in-jit" not in rules_of(findings)


def test_dtype_cast_out_of_scope_outside_model_code():
    """The same cast OUTSIDE mx_rcnn_tpu/models/ is out of scope — host
    tooling and tests cast freely."""
    findings = lint("""
        import jax.numpy as jnp

        def fold(x):
            return x.astype(jnp.float32)
    """)
    assert "dtype-cast-in-jit" not in rules_of(findings)


# ---------------------------------------------------------------------------
# health-host-pull
# ---------------------------------------------------------------------------

def test_health_pull_flags_probe_reduction_in_jit():
    """The ad-hoc in-graph probe: a reduction over isnan/isfinite inside
    traced code — both the jnp.any(...) and the .any() method spelling."""
    findings = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(state, grads):
            bad = jnp.any(jnp.isnan(grads))
            also_bad = jnp.isfinite(grads).all()
            return state, bad, also_bad
    """)
    flagged = [f for f in findings if f.rule == "health-host-pull"]
    assert len(flagged) == 2
    assert "train/health.py" in flagged[0].message


def test_health_pull_flags_item_pull_and_from_import():
    """The per-step host pull — float()/.item() of a probe — including
    the from-import alias spelling, via same-module trace reachability."""
    findings = lint("""
        from jax.numpy import isnan as nan_probe

        import jax
        import jax.numpy as jnp

        def _monitor(loss):
            return float(jnp.sum(nan_probe(loss)))

        def step(state, loss):
            return state, _monitor(loss)

        run = jax.jit(step)
    """)
    assert "health-host-pull" in rules_of(findings)


def test_health_pull_near_miss_masks_and_host_asserts():
    """Algorithmic masks (the ops/matching.py / ops/roi_align.py shape)
    consume the elementwise probe without reducing it to a health
    signal; host-side assertions are not trace-reachable. Neither
    flags."""
    findings = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def masked(x):
            guarded = jnp.where(jnp.isfinite(x), x, 0.0)
            bid = x * jnp.isfinite(x)
            return guarded + bid

        def host_gate(result):
            assert np.isfinite(result).all()
            return float(np.isnan(result).sum())
    """)
    assert "health-host-pull" not in rules_of(findings)


def test_health_pull_sanctioned_in_train_health():
    """train/health.py is THE home of in-graph health reductions — the
    exact flagged shape is legal there."""
    import textwrap

    from mx_rcnn_tpu.analysis import Settings, lint_source

    findings = lint_source(textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def finite_stats(x):
            return jnp.sum(jnp.isfinite(x))
    """), "mx_rcnn_tpu/train/health.py", Settings(), ALL_RULES)
    assert "health-host-pull" not in rules_of(findings)


# ---------------------------------------------------------------------------
# unbarriered-publish
# ---------------------------------------------------------------------------

def test_unbarriered_publish_flags_guarded_save_without_barrier():
    findings = lint("""
        from mx_rcnn_tpu.parallel.distributed import is_primary
        from mx_rcnn_tpu.train.checkpoint import save_checkpoint

        def emergency_stop(prefix, epoch, params, opt):
            if is_primary():
                save_checkpoint(prefix, epoch, params, opt)
    """)
    assert "unbarriered-publish" in rules_of(findings)
    msg = next(f for f in findings
               if f.rule == "unbarriered-publish").message
    assert "quorum.barrier" in msg


def test_unbarriered_publish_flags_process_index_comparison_guard():
    findings = lint("""
        import jax
        from mx_rcnn_tpu.train.checkpoint import save_checkpoint

        def boundary_save(prefix, epoch, params, opt):
            if jax.process_index() == 0:
                save_checkpoint(prefix, epoch, params, opt)
    """)
    assert sum(f.rule == "unbarriered-publish" for f in findings) == 1


def test_unbarriered_publish_near_miss_barrier_first():
    """The graftquorum contract: barrier, THEN primary-only publication
    — in the same function, lexically before the guarded save."""
    findings = lint("""
        from mx_rcnn_tpu.parallel.distributed import is_primary
        from mx_rcnn_tpu.train.checkpoint import save_checkpoint

        def coordinated_stop(quorum, prefix, epoch, params, opt):
            arrived = quorum.barrier("preempt/stop")
            if is_primary():
                save_checkpoint(prefix, epoch, params, opt,
                                meta={"hosts": sorted(arrived)})
    """)
    assert "unbarriered-publish" not in rules_of(findings)


def test_unbarriered_publish_near_miss_unguarded_and_foreign_saves():
    """Single-host saves (no primary guard) and non-checkpoint save()
    calls are out of scope — the rule targets the multi-host
    primary-only publication idiom specifically."""
    findings = lint("""
        from mx_rcnn_tpu.parallel.distributed import is_primary
        from mx_rcnn_tpu.train.checkpoint import save_checkpoint

        def single_host(prefix, epoch, params, opt):
            save_checkpoint(prefix, epoch, params, opt)

        def primary_log(log):
            if is_primary():
                log.save()
    """)
    assert "unbarriered-publish" not in rules_of(findings)


# ---------------------------------------------------------------------------
# graftsight: whole-program call graph (callgraph.py)
# ---------------------------------------------------------------------------

def _traced_names(files, rel_path):
    """Names of functions in ``rel_path`` the whole-program closure marks
    traced — the unit probe for callgraph.Program."""
    import textwrap as _tw

    from mx_rcnn_tpu.analysis import callgraph

    trees = {p: ast.parse(_tw.dedent(s)) for p, s in files.items()}
    program = callgraph.build_program(trees)
    return {getattr(n, "name", "<lambda>")
            for n in program.traced_nodes(rel_path)}


def test_callgraph_cross_module_direct_call():
    """jit root in a.py calls b.helper() — helper is traced in b.py."""
    names = _traced_names({
        "pkg/a.py": """
            import jax
            from pkg import b

            @jax.jit
            def entry(x):
                return b.helper(x)
        """,
        "pkg/b.py": """
            def helper(x):
                return inner(x)

            def inner(x):
                return x

            def unrelated(x):
                return x
        """,
    }, "pkg/b.py")
    assert names == {"helper", "inner"}  # transitively, not unrelated


def test_callgraph_aliased_from_import():
    """`from pkg.b import helper as h` — the alias resolves."""
    names = _traced_names({
        "pkg/a.py": """
            import jax
            from pkg.b import helper as h

            @jax.jit
            def entry(x):
                return h(x)
        """,
        "pkg/b.py": """
            def helper(x):
                return x
        """,
    }, "pkg/b.py")
    assert names == {"helper"}


def test_callgraph_method_on_imported_class():
    """Constructor assignment types the variable; obj.m() resolves to
    the imported class's method."""
    names = _traced_names({
        "pkg/a.py": """
            import jax
            from pkg.b import Model

            @jax.jit
            def entry(x):
                m = Model()
                return m.forward(x)
        """,
        "pkg/b.py": """
            class Model:
                def forward(self, x):
                    return self._head(x)

                def _head(self, x):
                    return x

                def save(self, path):
                    pass
        """,
    }, "pkg/b.py")
    assert names == {"forward", "_head"}  # self._head chased, save not


def test_callgraph_cycle_between_modules_terminates():
    """a.f -> b.g -> a.f again: the closure must terminate and mark
    both, not loop."""
    names_a = _traced_names({
        "pkg/a.py": """
            import jax
            from pkg import b

            @jax.jit
            def f(x, depth):
                return b.g(x, depth)
        """,
        "pkg/b.py": """
            from pkg import a

            def g(x, depth):
                return a.f(x, depth - 1)
        """,
    }, "pkg/b.py")
    assert names_a == {"g"}


def test_callgraph_unresolvable_dynamic_call_degrades():
    """getattr-dispatch and call-result callables resolve to nothing:
    no crash, and the dynamically-named function stays NOT traced
    (under-approximation, never over-flagging)."""
    names = _traced_names({
        "pkg/a.py": """
            import jax
            from pkg import b

            @jax.jit
            def entry(x, which):
                fn = getattr(b, which)
                g = b.make()()
                return fn(x) + g
        """,
        "pkg/b.py": """
            def maybe_target(x):
                return x

            def make():
                def inner():
                    return 0
                return inner
        """,
    }, "pkg/b.py")
    assert "maybe_target" not in names
    assert "inner" not in names  # call-result indirection: unresolvable
    assert "make" in names  # b.make() itself IS called directly


def test_cross_module_host_sync_fires_through_the_program():
    """Acceptance gate: a pre-existing jit rule (host-sync-in-jit) whose
    root and violation live in DIFFERENT modules — file-local tracing
    cannot see it; graftsight must."""
    files = {
        "pkg/train.py": """
            import jax
            from pkg import ops

            def step(state, x):
                return ops.normalize(state, x)

            run = jax.jit(step)
        """,
        "pkg/ops.py": """
            def normalize(state, x):
                scale = float(x.sum())   # host sync inside traced code
                return state, x / scale
        """,
    }
    findings = lint_files(files)
    assert any(f.rule == "host-sync-in-jit"
               and f.path == "pkg/ops.py" for f in findings)
    # and the same file linted ALONE (no program) stays clean — the
    # finding exists only through whole-program reachability
    alone = lint_source(textwrap.dedent(files["pkg/ops.py"]),
                        "pkg/ops.py", Settings(), ALL_RULES)
    assert "host-sync-in-jit" not in rules_of(alone)


# ---------------------------------------------------------------------------
# donation-hazard
# ---------------------------------------------------------------------------

def test_donation_hazard_flags_device_get_tree_into_local_donating_jit():
    findings = lint("""
        import jax

        def resume(step_fn, batch):
            run = jax.jit(step_fn, donate_argnums=(0,))
            state = jax.device_get(batch)      # host tree
            return run(state, batch)
    """)
    assert "donation-hazard" in rules_of(findings)


def test_donation_hazard_flags_np_tree_and_checkpoint_restore():
    findings = lint("""
        import jax
        import numpy as np
        from mx_rcnn_tpu.train.checkpoint import load_checkpoint

        def restore_and_go(step, path, batch):
            run = jax.jit(step, donate_argnums=(0, 1))
            state = load_checkpoint(path)        # configured source
            opt = np.zeros((4,))                 # np.* source
            return run(state, opt, batch)
    """)
    assert sum(f.rule == "donation-hazard" for f in findings) == 2


def test_donation_hazard_flags_cross_module_step_factory():
    """The PR 5/7 shape end-to-end: a restore flows into a donating
    step built by an IMPORTED factory (make_train_step's literal
    donate_argnums form)."""
    findings = lint_files({
        "pkg/steps.py": """
            import jax

            def make_step(model):
                def step(state, batch):
                    return state
                return jax.jit(step, donate_argnums=(0,))
        """,
        "pkg/fit.py": """
            from pkg.steps import make_step
            from mx_rcnn_tpu.train.checkpoint import load_checkpoint

            def fit(model, path, batch):
                step = make_step(model)
                state = load_checkpoint(path)
                return step(state, batch)
        """,
    })
    assert any(f.rule == "donation-hazard"
               and f.path == "pkg/fit.py" for f in findings)


def test_donation_hazard_near_miss_device_put_cleanses():
    findings = lint("""
        import jax
        from mx_rcnn_tpu.train.checkpoint import load_checkpoint

        def resume(step_fn, path, batch):
            run = jax.jit(step_fn, donate_argnums=(0,))
            state = load_checkpoint(path)
            state = jax.device_put(state)      # cleanse
            return run(state, batch)
    """)
    assert "donation-hazard" not in rules_of(findings)


def test_donation_hazard_near_miss_conditional_donate_is_unresolvable():
    """The sanctioned fit_detector CPU path: `donate_argnums=(0,) if
    donate else ()` is not a statically-donating call — no finding even
    with a host tree flowing in (on CPU the factory disables donation;
    flagging it would force a pointless device_put)."""
    findings = lint("""
        import jax
        from mx_rcnn_tpu.train.checkpoint import load_checkpoint

        def fit(step_fn, path, batch):
            donate = jax.default_backend() != "cpu"
            run = jax.jit(step_fn,
                          donate_argnums=(0,) if donate else ())
            state = load_checkpoint(path)
            return run(state, batch)
    """)
    assert "donation-hazard" not in rules_of(findings)


def test_donation_hazard_near_miss_rebind_from_sink_output():
    """state = run(state, b): AFTER the first (flagged) call the name is
    device-side — the steady-state loop does not re-flag every step."""
    findings = lint("""
        import jax
        import numpy as np

        def loop(step_fn, batches):
            run = jax.jit(step_fn, donate_argnums=(0,))
            state = np.zeros((4,))
            for b in batches:
                state = run(state, b)
            return state
    """)
    assert sum(f.rule == "donation-hazard" for f in findings) == 1


# ---------------------------------------------------------------------------
# thread-shared-mutation
# ---------------------------------------------------------------------------

def test_thread_race_flags_unlocked_counter_both_sides():
    """The PR 9 _note_pad shape: a counter bumped by the worker and
    reset by the main thread, no lock anywhere."""
    findings = lint("""
        import threading

        class Watch:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                self._n += 1

            def reset(self):
                self._n = 0
    """)
    assert sum(f.rule == "thread-shared-mutation" for f in findings) == 2


def test_thread_race_flags_one_unlocked_side_and_subscript_write():
    """Locking only ONE side is still a race; dict item writes count as
    writes to the attr."""
    findings = lint("""
        import threading

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = {}
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self._events["beat"] = 1   # locked: fine

            def clear(self):
                self._events["beat"] = 0       # unlocked main-side write
    """)
    flagged = [f for f in findings if f.rule == "thread-shared-mutation"]
    assert len(flagged) == 1
    # the clear() write, not the worker's locked one
    assert flagged[0].text.startswith('self._events["beat"] = 0')


def test_thread_race_flags_thread_subclass_run_and_transitive_callee():
    """Thread-subclass run() seeds the thread side, and self.m() calls
    from it drag the callee along."""
    findings = lint("""
        import threading

        class Pump(threading.Thread):
            def run(self):
                self._tick()

            def _tick(self):
                self.count = self.count + 1

            def restart(self):
                self.count = 0
    """)
    assert sum(f.rule == "thread-shared-mutation" for f in findings) == 2


def test_thread_race_near_miss_locked_both_sides_and_condition():
    """The repo's discipline (StallWatchdog / _PrefetchIterator): every
    cross-thread write under self._lock or a Condition — clean."""
    findings = lint("""
        import threading

        class Clean:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()
                self._n = 0
                self._slots = {}
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self._n += 1
                with self._cond:
                    self._slots[0] = 1

            def reset(self):
                with self._lock:
                    self._n = 0
                with self._cond:
                    self._slots.clear()
                    self._slots[0] = 0
    """)
    assert "thread-shared-mutation" not in rules_of(findings)


def test_thread_race_near_miss_init_writes_and_threadless_class():
    """__init__ writes happen-before start() (never flagged), and a
    class that constructs no thread is out of scope entirely."""
    findings = lint("""
        import threading

        class Lazy:
            def __init__(self):
                self._n = 0          # pre-start: happens-before
                self._t = threading.Thread(target=self._run)

            def _run(self):
                pass

        class NoThread:
            def bump(self):
                self._n += 1

            def also_bump(self):
                self._n += 2
    """)
    assert "thread-shared-mutation" not in rules_of(findings)

# ---------------------------------------------------------------------------
# blocking-queue-no-timeout (graftfeed): uncancellable queue waits
# ---------------------------------------------------------------------------

def test_queue_timeout_flags_blocking_get_and_put_in_thread_class():
    """The wedged-worker shape: a prefetcher hands batches over a
    Queue and both ends block forever — close() can never join."""
    findings = lint("""
        import queue
        import threading

        class Prefetcher:
            def __init__(self):
                self._q = queue.Queue(4)
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                self._q.put(self._load())      # producer wedges on full

            def __iter__(self):
                yield self._q.get()            # consumer wedges on empty
    """)
    assert sum(f.rule == "blocking-queue-no-timeout"
               for f in findings) == 2
    msg = next(f for f in findings
               if f.rule == "blocking-queue-no-timeout").message
    assert "timeout" in msg


def test_queue_timeout_flags_module_level_thread_target():
    """A plain worker function spun up via Thread(target=fn), blocking
    on a local queue."""
    findings = lint("""
        import threading
        from queue import Queue

        def pump(load):
            q = Queue()
            while True:
                q.put(load())

        threading.Thread(target=pump, daemon=True).start()
    """)
    assert sum(f.rule == "blocking-queue-no-timeout"
               for f in findings) == 1


def test_queue_timeout_near_miss_timeout_and_nonblocking_forms():
    """Every escape hatch is clean: timeout= on either op, block=False
    (keyword or positional), and the *_nowait spellings."""
    findings = lint("""
        import queue
        import threading

        class Prefetcher:
            def __init__(self):
                self._q = queue.Queue(4)
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                while True:
                    try:
                        self._q.put(1, timeout=0.1)
                        self._q.put_nowait(2)
                    except queue.Full:
                        continue

            def drain(self):
                try:
                    self._q.get(False)
                    self._q.get(block=False)
                    return self._q.get(timeout=0.1)
                except queue.Empty:
                    return None
    """)
    assert "blocking-queue-no-timeout" not in rules_of(findings)


def test_queue_timeout_near_miss_threadless_class_out_of_scope():
    """No thread constructed => a blocked call deadlocks loudly on the
    first call; single-threaded queue use is out of scope. dict.get/put
    lookalikes never count as queue receivers."""
    findings = lint("""
        import queue

        class Buffer:
            def __init__(self):
                self._q = queue.Queue()
                self._meta = {}

            def push(self, x):
                self._q.put(x)

            def pop(self):
                self._meta.get("hits")
                return self._q.get()
    """)
    assert "blocking-queue-no-timeout" not in rules_of(findings)


# ---------------------------------------------------------------------------
# wall-time-duration (grafttower): durations from wall-clock subtraction
# ---------------------------------------------------------------------------


def test_wall_time_duration_flags_time_time_subtraction():
    findings = lint("""
        import time

        def timed_step(run):
            t0 = time.time()
            run()
            return time.time() - t0
    """)
    assert "wall-time-duration" in rules_of(findings)
    msg = next(f for f in findings
               if f.rule == "wall-time-duration").message
    assert "monotonic" in msg


def test_wall_time_duration_flags_t_wall_field_and_self_attr():
    """Both spellings of a persisted wall sample: the event-record
    ``t_wall`` field (dict subscript / .get) and an attribute bound from
    time.time() elsewhere in the class."""
    findings = lint("""
        import time

        class Meter:
            def start(self):
                self._tic = time.time()

            def lap(self):
                return time.time() - self._tic

        def stream_gap(ev, prev):
            return ev["t_wall"] - prev.get("t_wall")
    """)
    assert sum(f.rule == "wall-time-duration" for f in findings) == 2


def test_wall_time_duration_near_miss_monotonic_clocks():
    """The fix the rule asks for must not itself flag: monotonic /
    perf_counter durations, including ones bound through locals."""
    findings = lint("""
        import time

        def timed_step(run):
            t0 = time.monotonic()
            run()
            return time.monotonic() - t0

        def profiled(run):
            tic = time.perf_counter()
            run()
            return time.perf_counter() - tic
    """)
    assert "wall-time-duration" not in rules_of(findings)


def test_wall_time_duration_near_miss_stamps_without_durations():
    """Wall stamps are fine when they aren't differenced: correlation
    stamps on records, and subtractions where the other operand's
    provenance is unknown (a deadline passed in by the caller)."""
    findings = lint("""
        import time

        def stamp(record):
            record["t_wall"] = time.time()
            return record

        def remaining(deadline):
            return time.time() - deadline if deadline else None
    """)
    assert "wall-time-duration" not in rules_of(findings)
