"""Anchor generation vs the classic published values."""

import numpy as np

from mx_rcnn_tpu.ops.anchors import generate_anchors, anchor_grid

# The canonical output of generate_anchors(16, (0.5,1,2), (8,16,32)) —
# published in the original py-faster-rcnn docstring and reproduced by the
# reference's rcnn/processing/generate_anchor.py.
CANONICAL = np.array(
    [
        [-84.0, -40.0, 99.0, 55.0],
        [-176.0, -88.0, 191.0, 103.0],
        [-360.0, -184.0, 375.0, 199.0],
        [-56.0, -56.0, 71.0, 71.0],
        [-120.0, -120.0, 135.0, 135.0],
        [-248.0, -248.0, 263.0, 263.0],
        [-36.0, -80.0, 51.0, 95.0],
        [-80.0, -168.0, 95.0, 183.0],
        [-168.0, -344.0, 183.0, 359.0],
    ]
)


def test_generate_anchors_canonical():
    a = generate_anchors(16, (0.5, 1.0, 2.0), (8, 16, 32))
    assert a.shape == (9, 4)
    assert np.allclose(a, CANONICAL)


def test_grid_shape_and_order():
    g = anchor_grid(2, 3, stride=16)
    assert g.shape == (2 * 3 * 9, 4)
    base = generate_anchors()
    # First A anchors = base anchors at shift (0,0).
    assert np.allclose(g[:9], base)
    # Anchor block at (h=0, w=1) is base + (16, 0).
    assert np.allclose(g[9:18], base + np.array([16, 0, 16, 0], np.float32))
    # Anchor block at (h=1, w=0) is base + (0, 16).
    assert np.allclose(g[27:36], base + np.array([0, 16, 0, 16], np.float32))


def test_single_scale_fpn_anchor_count():
    g = anchor_grid(4, 4, stride=4, ratios=(0.5, 1.0, 2.0), scales=(8,))
    assert g.shape == (4 * 4 * 3, 4)
