"""bench.py partial-results flush (the BENCH_r05 rc=124 lesson).

Every completed config's row must be on disk BEFORE the next one starts,
so a killed sweep (TPU outage, driver timeout) keeps its finished
measurements. Covered two ways: in-process (the flush file is readable
and complete after every row) and for real — a subprocess SIGKILLs itself
mid-sweep and the completed rows are found on disk.
"""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_bench_update_config_produces_numbers():
    """The update microbench must yield real tree AND flat timings — a
    donation-ordering bug once deleted the param arrays before the flat
    state was built, so both update_* recipes silently recorded errors."""
    from dataclasses import replace

    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("resnet50", "synthetic", **{
        "train.rpn_pre_nms_top_n": 128, "train.rpn_post_nms_top_n": 32,
        "train.batch_rois": 16, "train.max_gt_boxes": 4,
        "train.batch_images": 1, "network.anchor_scales": (2, 4),
        "image.pad_shape": (64, 64)})
    cfg = cfg.with_updates(
        train=replace(cfg.train, compute_dtype="f32"))
    out = bench.bench_update_config(cfg, reps=1, iters=2)
    assert out["tree_ms"] > 0 and out["flat_ms"] > 0
    assert out["param_leaves"] > 100
    assert out["optimizer"] == "sgd"


import pytest


@pytest.mark.compile_heavy
def test_bench_config_rows_carry_cost_fields():
    """The graftprof acceptance gate (CPU backend path): every bench row
    carries `mfu`, `hbm_bytes` and `pad_waste` computed from the
    compiled executable's cost_analysis()/memory_analysis(), plus the
    compile-zoo accounting (`compile_s`/`n_executables`)."""
    from dataclasses import replace

    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("resnet50", "synthetic", **{
        "train.rpn_pre_nms_top_n": 128, "train.rpn_post_nms_top_n": 32,
        "train.batch_rois": 16, "train.max_gt_boxes": 4,
        "train.batch_images": 8,  # the CPU mesh shards over 8 devices
        "network.anchor_scales": (2, 4),
        "image.pad_shape": (64, 64)})
    cfg = cfg.with_updates(
        train=replace(cfg.train, compute_dtype="f32"))
    row = bench.bench_config(cfg, reps=1, iters=2)
    assert row["img_s_per_chip"] > 0
    assert row["mfu"] is not None and row["mfu"] >= 0
    # graftcast: every row names its compute dtype (this cfg pins f32),
    # the ledger's cross-dtype comparison guard
    assert row["compute_dtype"] == "f32"
    assert row["hbm_bytes"] > 0
    # make_batch's content size is canvas-proportional (600/640 x
    # 1000/1024), so the padding fraction is a fixed known quantity
    assert row["pad_waste"] == pytest.approx(
        1 - (64 * 600 // 640) * (64 * 1000 // 1024) / (64 * 64), abs=1e-3)
    assert row["compile_s"] >= 0 and row["n_executables"] >= 0


def test_run_sweep_on_row_sees_every_completed_row(tmp_path):
    """The ledger hook: on_row fires per completed config — including
    error rows — in sweep order (bench.main appends each to the perf
    ledger the moment it lands, the partial.json durability contract)."""
    seen = []

    def runner(cfg):
        if cfg == "boom":
            raise RuntimeError("relay dropped")
        return {"img_s_per_chip": 3.0}

    bench.run_sweep({"a": "a", "b": "boom"}, runner, attempts=1,
                    on_row=lambda name, row: seen.append((name, row)))
    assert [s[0] for s in seen] == ["a", "b"]
    assert seen[0][1]["img_s_per_chip"] == 3.0
    assert "error" in seen[1][1]


def test_run_sweep_flushes_after_every_config(tmp_path):
    flush = str(tmp_path / "partial.json")
    seen = []

    def runner(cfg):
        if seen:  # previous rows must already be durable
            with open(flush, "r", encoding="utf-8") as fh:
                on_disk = json.load(fh)
            assert all(k in on_disk for k in seen), (seen, on_disk)
        if cfg == "boom":
            raise RuntimeError("relay dropped")
        seen.append(cfg)
        return {"img_s_per_chip": 1.0, "which": cfg}

    detail = bench.run_sweep({"a": "a", "b": "boom", "c": "c"}, runner,
                             flush_path=flush, attempts=1)
    with open(flush, "r", encoding="utf-8") as fh:
        on_disk = json.load(fh)
    assert set(on_disk) == {"a", "b", "c"}
    assert on_disk["b"]["error"].startswith("RuntimeError")
    assert detail == on_disk


def test_run_sweep_retries_then_records_error(tmp_path):
    calls = []

    def runner(cfg):
        calls.append(cfg)
        raise ValueError("always down")

    detail = bench.run_sweep({"x": "x"}, runner, attempts=2)
    assert len(calls) == 2  # one retry, like the relay-drop policy
    assert "error" in detail["x"]


def test_flush_partial_is_atomic(tmp_path):
    path = str(tmp_path / "p.json")
    bench.flush_partial(path, {"a": 1})
    bench.flush_partial(path, {"a": 1, "b": 2})
    with open(path, "r", encoding="utf-8") as fh:
        assert json.load(fh) == {"a": 1, "b": 2}
    assert not os.path.exists(path + ".tmp")


def test_flush_partial_coerces_non_json_values(tmp_path):
    """A row with a stray np scalar must degrade in place, not raise and
    kill the rest of the sweep."""
    import numpy as np

    path = str(tmp_path / "p.json")
    bench.flush_partial(path, {"a": {"ms": np.float32(1.5),
                                     "n": np.int64(3)}})
    with open(path, "r", encoding="utf-8") as fh:
        row = json.load(fh)["a"]
    assert row["ms"] == 1.5 and row["n"] == 3


def test_partial_rows_survive_sigkill(tmp_path):
    """The acceptance gate: kill the run mid-sweep, find the completed
    rows on disk. SIGKILL (no atexit, no finally) is the honest analog of
    the rc=124 outage that ate BENCH_r05."""
    flush = str(tmp_path / "partial.json")
    script = textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import bench

        def runner(cfg):
            if cfg == "die":
                os.kill(os.getpid(), signal.SIGKILL)
            return {{"img_s_per_chip": 2.0, "which": cfg}}

        bench.run_sweep({{"first": "first", "die": "die", "never": "never"}},
                        runner, flush_path={flush!r}, attempts=1)
        print("UNREACHABLE")
    """)
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          timeout=110)
    assert proc.returncode == -9, (proc.returncode, proc.stderr[-2000:])
    assert "UNREACHABLE" not in proc.stdout
    with open(flush, "r", encoding="utf-8") as fh:
        on_disk = json.load(fh)
    assert on_disk == {"first": {"img_s_per_chip": 2.0, "which": "first"}}
