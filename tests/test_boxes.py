"""Unit tests for box geometry ops vs hand-computed / numpy references."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.boxes import bbox_transform, bbox_pred, clip_boxes, bbox_overlaps


def np_bbox_overlaps(boxes, query):
    """Straight numpy port of the reference O(N,K) IoU (inclusive widths)."""
    n, k = boxes.shape[0], query.shape[0]
    out = np.zeros((n, k), dtype=np.float64)
    for i in range(n):
        for j in range(k):
            iw = min(boxes[i, 2], query[j, 2]) - max(boxes[i, 0], query[j, 0]) + 1
            ih = min(boxes[i, 3], query[j, 3]) - max(boxes[i, 1], query[j, 1]) + 1
            if iw > 0 and ih > 0:
                ua = (
                    (boxes[i, 2] - boxes[i, 0] + 1) * (boxes[i, 3] - boxes[i, 1] + 1)
                    + (query[j, 2] - query[j, 0] + 1) * (query[j, 3] - query[j, 1] + 1)
                    - iw * ih
                )
                out[i, j] = iw * ih / ua
    return out


class TestOverlaps:
    def test_identical_box(self):
        b = jnp.array([[0.0, 0.0, 9.0, 9.0]])
        iou = bbox_overlaps(b, b)
        assert np.allclose(iou, 1.0)

    def test_disjoint(self):
        a = jnp.array([[0.0, 0.0, 9.0, 9.0]])
        b = jnp.array([[20.0, 20.0, 29.0, 29.0]])
        assert np.allclose(bbox_overlaps(a, b), 0.0)

    def test_half_overlap_inclusive(self):
        # [0,9]x[0,9] (area 100) vs [5,14]x[0,9] (area 100): inter 5x10=50,
        # union 150 -> IoU 1/3 under the inclusive convention.
        a = jnp.array([[0.0, 0.0, 9.0, 9.0]])
        b = jnp.array([[5.0, 0.0, 14.0, 9.0]])
        assert np.allclose(bbox_overlaps(a, b), 50.0 / 150.0)

    def test_vs_numpy_random(self, rng):
        boxes = rng.uniform(0, 100, (40, 4))
        boxes[:, 2:] += boxes[:, :2]
        query = rng.uniform(0, 100, (23, 4))
        query[:, 2:] += query[:, :2]
        got = np.asarray(bbox_overlaps(jnp.array(boxes), jnp.array(query)))
        want = np_bbox_overlaps(boxes, query)
        assert np.allclose(got, want, atol=1e-5)


class TestTransformRoundTrip:
    def test_transform_identity(self):
        b = jnp.array([[10.0, 10.0, 50.0, 30.0]])
        d = bbox_transform(b, b)
        assert np.allclose(d, 0.0, atol=1e-6)

    def test_pred_inverts_transform(self, rng):
        ex = rng.uniform(0, 200, (30, 4)).astype(np.float32)
        ex[:, 2:] = ex[:, :2] + np.abs(ex[:, 2:] - ex[:, :2]) + 5
        gt = rng.uniform(0, 200, (30, 4)).astype(np.float32)
        gt[:, 2:] = gt[:, :2] + np.abs(gt[:, 2:] - gt[:, :2]) + 5
        deltas = bbox_transform(jnp.array(ex), jnp.array(gt))
        back = bbox_pred(jnp.array(ex), deltas)
        assert np.allclose(back, gt, atol=1e-2)

    def test_known_values(self):
        # ex box (0,0,9,9): w=h=10, ctr (4.5,4.5).
        # gt box (5,5,14,14): w=h=10, ctr (9.5,9.5).
        # dx = 5/10 = 0.5, dw = log(1) = 0.
        ex = jnp.array([[0.0, 0.0, 9.0, 9.0]])
        gt = jnp.array([[5.0, 5.0, 14.0, 14.0]])
        d = np.asarray(bbox_transform(ex, gt))
        assert np.allclose(d, [[0.5, 0.5, 0.0, 0.0]], atol=1e-6)

    def test_multiclass_pred(self):
        # K=2 classes: deltas (N, 8); each group decoded against the same box.
        ex = jnp.array([[0.0, 0.0, 9.0, 9.0]])
        deltas = jnp.array([[0.0] * 4 + [0.5, 0.5, 0.0, 0.0]])
        out = np.asarray(bbox_pred(ex, deltas))
        assert np.allclose(out[0, :4], [0, 0, 9, 9], atol=1e-5)
        assert np.allclose(out[0, 4:], [5, 5, 14, 14], atol=1e-5)


class TestClip:
    def test_clip(self):
        b = jnp.array([[-5.0, -5.0, 120.0, 150.0]])
        out = np.asarray(clip_boxes(b, (100.0, 110.0)))
        assert np.allclose(out, [[0.0, 0.0, 109.0, 99.0]])

    def test_jit_consistency(self):
        b = jnp.array([[-5.0, 3.0, 120.0, 90.0]])
        eager = clip_boxes(b, (100.0, 110.0))
        jitted = jax.jit(lambda x: clip_boxes(x, (100.0, 110.0)))(b)
        assert np.allclose(eager, jitted)
