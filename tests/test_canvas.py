"""graftcanvas — whole-batch canvas packing (data/canvas.py, ops/canvas.py,
the packed loader path and the packed model forwards).

The three acceptance gates of the feature, all on CPU:
- packed forward == per-image bucketed forward (loss rtol well under 1e-4
  for C4 and FPN — in fact f32-rounding-level, because placement masking
  reproduces the bucketed canvas-edge zero padding exactly);
- border isolation: no proposal crosses a placement border;
- compile collapse: a multi-scale config trains through ONE compiled
  train-step shape (the orientation x scale pad-bucket zoo is gone).

Budget notes: module-scope model/params fixtures, numpy perturbation,
64-128 px shapes, tiny proposal budgets (memory: tier-1 is budget-bound).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import Config, ImageConfig, generate_config
from mx_rcnn_tpu.data import canvas as dcanvas
from mx_rcnn_tpu.data.loader import AnchorLoader, ROIIter
from mx_rcnn_tpu.models import faster_rcnn as c4
from mx_rcnn_tpu.models import fpn as F
from mx_rcnn_tpu.obs import compile_track
from mx_rcnn_tpu.ops.anchors import anchor_grid
from mx_rcnn_tpu.ops.proposal import generate_proposals_packed


# ---------------------------------------------------------------------------
# Planner / config contract (pure host)
# ---------------------------------------------------------------------------


def test_plan_plane_aligned_and_separated():
    offs = dcanvas.plan_plane([(64, 96), (96, 128)], (192, 128),
                              gap=16, align=16)
    assert offs is not None
    for (y, x) in offs:
        assert y % 16 == 0 and x % 16 == 0
    # FFD puts the taller rect first; both fit with a >= gap separation.
    (y0, x0), (y1, x1) = offs
    assert {(y0, x0), (y1, x1)} == {(112, 0), (0, 0)}
    # overflow → None
    assert dcanvas.plan_plane([(160, 96), (96, 96)], (192, 128),
                              gap=16, align=16) is None


def test_plan_batch_scale_to_fit_and_hopeless():
    spec = dcanvas.CanvasSpec((128, 128), gap=16, align=16, images=2)

    def sizes_at(fit):
        return [(int(100 * fit), int(100 * fit)),
                (int(100 * fit), int(100 * fit))]

    placements, fit, sizes = dcanvas.plan_batch(sizes_at, 2, spec)
    assert fit < 1.0  # two 100px squares cannot share a 128px canvas
    assert len(placements) == 2
    for (pl, y, x), (h, w) in zip(placements, sizes):
        assert pl == 0 and y + h <= 128 and x + w <= 128
    # a canvas that can never fit raises with the real cause
    tiny = dcanvas.CanvasSpec((16, 16), gap=16, align=16, images=1)
    with pytest.raises(ValueError, match="mis-sized"):
        dcanvas.plan_batch(lambda f: [(400, 400)], 1, tiny)


def _canvas_cfg(net="resnet50", **over):
    base = {
        "image.scales": ((64, 96),),
        "image.pad_shape": (64, 96),
        "image.canvas_pack": True,
        "image.canvas_shape": (160, 96),
        "image.canvas_images": 2,
        "train.batch_images": 2,
    }
    base.update(over)
    return generate_config(net, "synthetic", **base)


def test_validate_accepts_groupnorm_from_scratch():
    """Regression: --from-scratch flips norm to GroupNorm — canvas_pack's
    validate must ACCEPT it (canvas-pooled stats are the same
    approximation class as the zero padding already in the bucketed
    GroupNorm stats), not refuse the whole from-scratch profile."""
    cfg = _canvas_cfg(**{"network.norm": "group", "network.freeze_at": 0})
    spec = dcanvas.validate_canvas_pack(cfg)
    assert spec.shape == (160, 96) and spec.images == 2
    # ...and the loader (which validates on construction) builds too.
    loader = AnchorLoader(_mixed_roidb(4), cfg, num_shards=1)
    assert loader._canvas_spec is not None


def test_validate_rejections():
    with pytest.raises(ValueError, match="DETR"):
        dcanvas.validate_canvas_pack(
            _canvas_cfg("detr_r50", **{"image.canvas_shape": (192, 96)}))
    with pytest.raises(ValueError, match="multiple"):
        dcanvas.validate_canvas_pack(
            _canvas_cfg(**{"image.canvas_shape": (150, 96)}))
    with pytest.raises(ValueError, match="short side"):
        dcanvas.validate_canvas_pack(
            _canvas_cfg(**{"image.canvas_shape": (32, 32)}))
    with pytest.raises(ValueError, match="positive multiple"):
        # -16 % 16 == 0: without the sign check a negative gap would
        # validate and the planner would emit OVERLAPPING placements
        dcanvas.validate_canvas_pack(_canvas_cfg(**{"image.canvas_gap": -16}))
    with pytest.raises(NotImplementedError, match="ROIIter"):
        ROIIter(_mixed_roidb(4), _canvas_cfg(), num_shards=1)


# ---------------------------------------------------------------------------
# Packed loader (host assembly + pad counters)
# ---------------------------------------------------------------------------


def _mixed_roidb(n):
    """Landscape-ish mixed-size synthetic entries, content well below the
    square pad bucket — the measured-pad-waste shape of the ROADMAP item."""
    rs = np.random.RandomState(0)
    dims = [(48, 80), (64, 96), (48, 96), (56, 88)]
    out = []
    for i in range(n):
        h, w = dims[i % len(dims)]
        out.append({
            "image_data": rs.uniform(0, 255, (h, w, 3)).astype(np.float32),
            "height": h, "width": w,
            "boxes": np.asarray([[4.0, 4.0, w // 2, h // 2]], np.float32),
            "gt_classes": np.asarray([1 + i % 3], np.int32),
        })
    return out


def _loader_cfg(packed: bool):
    over = {
        "image.scales": ((48, 96),),
        "image.pad_shape": (96, 96),
        "train.batch_images": 2,
        "train.max_gt_boxes": 4,
        "train.shuffle": False,
    }
    if packed:
        over.update({"image.canvas_pack": True,
                     "image.canvas_shape": (128, 96),
                     "image.canvas_images": 2})
    return generate_config("resnet50", "synthetic", **over)


def test_packed_loader_batch_contract():
    cfg = _loader_cfg(packed=True)
    with AnchorLoader(_mixed_roidb(4), cfg, num_shards=1) as loader:
        batch = next(iter(loader))
    assert batch["image"].shape == (1, 128, 96, 3)
    assert batch["im_info"].shape == (1, 2, 5)
    assert batch["gt_boxes"].shape == (1, 2, 4, 4)
    for slot in range(2):
        h, w, scale, y0, x0 = batch["im_info"][0, slot]
        assert y0 % 16 == 0 and x0 % 16 == 0
        assert y0 + h <= 128 and x0 + w <= 96
        assert scale > 0
        # gt boxes live inside the placement rect (canvas coordinates)
        gtb = batch["gt_boxes"][0, slot][batch["gt_valid"][0, slot]]
        assert np.all(gtb[:, 0] >= x0) and np.all(gtb[:, 1] >= y0)
        assert np.all(gtb[:, 2] <= x0 + w) and np.all(gtb[:, 3] <= y0 + h)
    # placements are disjoint and gap pixels are exactly zero
    m = np.zeros((128, 96), np.int32)
    for slot in range(2):
        h, w, _, y0, x0 = batch["im_info"][0, slot].astype(int)
        m[y0:y0 + h, x0:x0 + w] += 1
    assert m.max() == 1
    assert np.all(batch["image"][0][m == 0] == 0.0)


def test_packed_pad_waste_below_bucketed():
    """Acceptance: on the same mixed-size roidb the packed loader's
    measured canvas waste is below the bucketed loader's bucket waste."""
    roidb = _mixed_roidb(8)
    with AnchorLoader(roidb, _loader_cfg(False), num_shards=1) as lb:
        for _ in lb:
            pass
        bucketed = lb.pad_waste_stats()
    with AnchorLoader(roidb, _loader_cfg(True), num_shards=1) as lp:
        for _ in lp:
            pass
        packed = lp.pad_waste_stats()
    assert bucketed is not None and packed is not None
    assert packed["pad_waste"] < bucketed["pad_waste"] - 0.05
    # graftprof's batch accountant agrees with the loader's counters on
    # the packed contract (planes counted once, not per im_info row)
    from mx_rcnn_tpu.obs.costs import batch_pad_waste

    cfg = _loader_cfg(True)
    with AnchorLoader(roidb, cfg, num_shards=1) as lp2:
        batch = next(iter(lp2))
    pw = batch_pad_waste(batch)
    assert pw["canvas_px"] == 128 * 96  # ONE plane
    assert 0.0 < pw["pad_waste"] < 1.0


# ---------------------------------------------------------------------------
# Border isolation (packed proposals)
# ---------------------------------------------------------------------------


def test_packed_proposals_stay_inside_placements():
    rs = np.random.RandomState(3)
    anchors = jnp.asarray(anchor_grid(10, 6, stride=16, base_size=16,
                                      ratios=(0.5, 1.0, 2.0), scales=(2, 4)))
    n = anchors.shape[0]
    # two images in one plane: rects (64x96 @ 0,0) and (64x96 @ 96,0)
    info = jnp.asarray([[64, 96, 1.0, 0, 0], [64, 96, 1.0, 96, 0]],
                       jnp.float32)
    scores = jnp.asarray(rs.uniform(size=(2, n)), jnp.float32)
    deltas = jnp.asarray(rs.normal(0, 0.5, (2, n, 4)), jnp.float32)
    rois, valid, _ = generate_proposals_packed(
        scores, deltas, info, anchors, pre_nms_top_n=128,
        post_nms_top_n=32, nms_thresh=0.7, min_size=4)
    rois, valid = np.asarray(rois), np.asarray(valid)
    assert valid.any()
    for i, (h, w, _, y0, x0) in enumerate(np.asarray(info)):
        r = rois[i][valid[i]]
        assert len(r)
        assert np.all(r[:, 0] >= x0) and np.all(r[:, 2] <= x0 + w - 1)
        assert np.all(r[:, 1] >= y0) and np.all(r[:, 3] <= y0 + h - 1)


def test_fpn_packed_proposals_stay_inside_placements():
    rs = np.random.RandomState(4)
    cfg = generate_config("resnet50_fpn", "synthetic", **{
        "image.scales": ((64, 128),), "image.pad_shape": (64, 128),
        "network.anchor_scales": (2,), "network.proposal_topk": "exact",
        "train.fpn_rpn_pre_nms_per_level": 64,
        "train.rpn_post_nms_top_n": 16,
    })
    shapes = {lv: (256 // 2 ** lv, 128 // 2 ** lv) for lv in F.RPN_LEVELS}
    anchors = F.pyramid_anchors(shapes, cfg)
    rpn_out = {}
    for lv, (h, w) in shapes.items():
        rpn_out[lv] = (
            jnp.asarray(rs.normal(0, 1, (1, h, w, 6)), jnp.float32),
            jnp.asarray(rs.normal(0, 0.5, (1, h, w, 12)), jnp.float32))
    info = jnp.asarray([[64, 128, 1.0, 0, 0], [64, 128, 1.0, 128, 0]],
                       jnp.float32)
    plane_of = jnp.zeros((2,), jnp.int32)
    rois, valid, _ = F.fpn_proposals_packed(rpn_out, anchors, info,
                                            plane_of, cfg, train=True)
    rois, valid = np.asarray(rois), np.asarray(valid)
    assert valid.any()
    for i, (h, w, _, y0, x0) in enumerate(np.asarray(info)):
        r = rois[i][valid[i]]
        assert np.all(r[:, 0] >= x0) and np.all(r[:, 2] <= x0 + w - 1)
        assert np.all(r[:, 1] >= y0) and np.all(r[:, 3] <= y0 + h - 1)


# ---------------------------------------------------------------------------
# Exactness: packed forward == bucketed forward (C4 + FPN)
# ---------------------------------------------------------------------------


def _perturb(params, seed=1, sigma=0.02):
    """Numpy param perturbation (per-leaf jax.random costs seconds on
    big trees). Exactness holds for ARBITRARY frozen-BN parameters —
    placements see implicit-zero boundaries exactly like bucket edges —
    so every leaf is perturbed, norms included."""
    rs = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) + rs.normal(0, sigma, x.shape)
        .astype(x.dtype), params)


def _pair_batches(hw, align):
    """Two same-bucket images + their packed single-plane counterpart.
    Content fills the bucket exactly, so the bucketed forward has no pad
    cells — the geometry where packed == bucketed is provable (and
    gated here) bit-for-bit; mixed-size placements are covered by the
    border-isolation tests above."""
    h, w = hw
    g = 8
    rs = np.random.RandomState(2)
    imgs = rs.randn(2, h, w, 3).astype(np.float32)
    gtb = np.zeros((2, g, 4), np.float32)
    gtb[0, :2] = [[10, 10, w - 45, h - 20], [40, 20, w - 5, h - 4]]
    gtb[1, :2] = [[5, 8, 30, 30], [w // 2, h // 2, w - 8, h - 6]]
    gtc = np.zeros((2, g), np.int32)
    gtc[:, :2] = [[1, 2], [2, 1]]
    gtv = np.zeros((2, g), bool)
    gtv[:, :2] = True
    bucketed = {
        "image": imgs,
        "im_info": np.asarray([[h, w, 1.0]] * 2, np.float32),
        "gt_boxes": gtb, "gt_classes": gtc, "gt_valid": gtv,
    }
    off = dcanvas.align_up(h + align, align)
    canvas = np.zeros((1, off + dcanvas.align_up(h, align), w, 3),
                      np.float32)
    canvas[0, :h] = imgs[0]
    canvas[0, off:off + h] = imgs[1]
    info = np.zeros((1, 2, 5), np.float32)
    info[0, 0] = (h, w, 1.0, 0, 0)
    info[0, 1] = (h, w, 1.0, off, 0)
    gtb_p = gtb.copy()
    gtb_p[1, :, 1] += off
    gtb_p[1, :, 3] += off
    packed = {
        "image": canvas, "im_info": info, "gt_boxes": gtb_p[None],
        "gt_classes": gtc[None], "gt_valid": gtv[None],
    }
    return bucketed, packed


@pytest.fixture(scope="module")
def c4_cfg():
    return _canvas_cfg(**{
        "train.compute_dtype": "f32",
        "network.anchor_scales": (2, 4),
        "train.rpn_batch_size": 1024,  # keep-all: neutralizes the anchor
        # subsample's grid-size-dependent uniform draws (canvas grid !=
        # bucket grid); everything downstream is then bit-comparable.
        "train.rpn_pre_nms_top_n": 300,
        "train.rpn_post_nms_top_n": 32,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
    })


@pytest.fixture(scope="module")
def c4_model_params(c4_cfg):
    model = c4.build_model(c4_cfg)
    params = _perturb(c4.init_params(model, c4_cfg, jax.random.PRNGKey(0)))
    return model, params


def test_packed_matches_bucketed_c4(c4_cfg, c4_model_params):
    model, params = c4_model_params
    bucketed, packed = _pair_batches((64, 96), align=16)
    rng = jax.random.PRNGKey(7)
    fwd = jax.jit(lambda p, b, r: c4.forward_train(model, p, b, r, c4_cfg))
    lb, auxb = fwd(params, bucketed, rng)
    lp, auxp = fwd(params, packed, rng)
    assert float(auxb["rpn_cls_loss"]) > 0  # live RPN targets, not a 0==0
    np.testing.assert_allclose(float(lb), float(lp), rtol=1e-4)
    for k in ("rpn_cls_loss", "rpn_bbox_loss",
              "rcnn_cls_loss", "rcnn_bbox_loss"):
        np.testing.assert_allclose(float(auxb[k]), float(auxp[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


@pytest.fixture(scope="module")
def fpn_cfg():
    return generate_config("resnet50_fpn", "synthetic", **{
        "image.scales": ((64, 128),),
        "image.pad_shape": (64, 128),
        "image.pad_shapes": (),
        "image.canvas_pack": True,
        "image.canvas_shape": (256, 128),
        "image.canvas_images": 2,
        "train.compute_dtype": "f32",
        "network.anchor_scales": (2,),
        "network.proposal_topk": "exact",  # approx_max_k membership is
        # grid-size-dependent; exactness needs the deterministic top-k
        "train.batch_images": 2,
        "train.rpn_batch_size": 4096,
        "train.fpn_rpn_pre_nms_per_level": 128,
        "train.rpn_post_nms_top_n": 32,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
    })


def test_packed_matches_bucketed_fpn(fpn_cfg):
    model = F.build_fpn_model(fpn_cfg)
    params = _perturb(F.init_fpn_params(model, fpn_cfg,
                                        jax.random.PRNGKey(0)))
    bucketed, packed = _pair_batches((64, 128), align=64)
    rng = jax.random.PRNGKey(7)
    fwd = jax.jit(lambda p, b, r: F.forward_train(model, p, b, r, fpn_cfg))
    lb, auxb = fwd(params, bucketed, rng)
    lp, auxp = fwd(params, packed, rng)
    assert float(auxb["rpn_cls_loss"]) > 0
    np.testing.assert_allclose(float(lb), float(lp), rtol=1e-4)
    for k in ("rpn_cls_loss", "rpn_bbox_loss",
              "rcnn_cls_loss", "rcnn_bbox_loss"):
        np.testing.assert_allclose(float(auxb[k]), float(auxp[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


# ---------------------------------------------------------------------------
# Compile collapse: one train-step shape across the scale zoo
# ---------------------------------------------------------------------------


def test_multiscale_canvas_single_compiled_shape(c4_cfg, c4_model_params):
    """Two scale buckets, orientation-mixed roidb — the bucketed loader
    would compile one step per (scale x orientation) bucket; the packed
    loader feeds ONE canvas shape, so the whole multi-scale stream runs
    through a single compiled train step (compile_track.count())."""
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = _canvas_cfg(**{
        "image.scales": ((48, 96), (64, 96)),
        "image.pad_shapes": (),
        "image.canvas_shape": (160, 96),
        "train.compute_dtype": "f32",
        "network.anchor_scales": (2, 4),
        "train.rpn_pre_nms_top_n": 64,
        "train.rpn_post_nms_top_n": 16,
        "train.batch_rois": 16,
        "train.max_gt_boxes": 4,
        "train.shuffle": False,
    })
    roidb = _mixed_roidb(8)
    with AnchorLoader(roidb, cfg, num_shards=1, seed=0) as loader:
        loader.set_epoch(0)
        batches = list(loader)
    # multi-scale draw happened, yet every batch has the ONE canvas shape
    shapes = {tuple(b["image"].shape) for b in batches}
    assert shapes == {(1, 160, 96, 3)}
    scales = {round(float(b["im_info"][0, 0, 2]), 3) for b in batches}
    assert len(scales) > 1  # genuinely different scale draws
    # ...while the BUCKETED loader over the same roidb/scales feeds the
    # shape zoo this feature collapses (>= one bucket per scale draw).
    bcfg = cfg.with_updates(image=ImageConfig(
        scales=cfg.image.scales, pad_shape=(96, 96),
        pad_shapes=((48, 96), (64, 96))))
    with AnchorLoader(roidb, bcfg, num_shards=1, seed=0) as bl:
        bl.set_epoch(0)
        bucket_shapes = {tuple(b["image"].shape) for b in bl}
    assert len(bucket_shapes) > 1

    model, params = c4_model_params  # same tree; cfg drives the forward
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    mesh = create_mesh("1")
    step_fn = make_train_step(model, cfg, mesh=mesh, donate=False)
    # Two dispatches cover both scale draws (seed-0 order starts 0, 1);
    # the remaining batches add no coverage, only tier-1 wall time.
    two = [batches[0], next(b for b in batches[1:]
                            if float(b["im_info"][0, 0, 2])
                            != float(batches[0]["im_info"][0, 0, 2]))]
    with compile_track.count() as cc:
        for i, batch in enumerate(two):
            sharded = shard_batch(batch, mesh)
            state, metrics = step_fn(state, sharded,
                                     jax.random.PRNGKey(10 + i))
        float(np.asarray(metrics["TotalLoss"]))
    # ONE executable for the whole multi-scale stream (0 on a warm
    # persistent cache — never one per scale bucket). The pjit cache may
    # hold a second ENTRY (first call sees host-numpy state, later calls
    # committed device state — fit_detector steady state), but both lower
    # to the same program: no second backend compile.
    assert cc.n <= 1
    assert step_fn._cache_size() <= 2
