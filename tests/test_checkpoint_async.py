"""Async checkpointing (train/checkpoint.py::CheckpointWriter).

The async writer must produce byte-identical on-disk state to the
synchronous `save_checkpoint` (same raw-delta bbox_pred contract) and be
durable after close().
"""

import jax
import numpy as np
import pytest

from mx_rcnn_tpu.train.checkpoint import (
    CheckpointWriter,
    load_checkpoint,
    save_checkpoint,
)


def _tree(rng):
    return {
        "params": {
            "backbone": {"kernel": rng.randn(3, 3, 4, 8).astype(np.float32)},
            "bbox_pred": {
                "kernel": rng.randn(16, 12).astype(np.float32),
                "bias": rng.randn(12).astype(np.float32),
            },
        }
    }


def test_async_save_matches_sync(tmp_path, rng):
    params = _tree(rng)
    opt_state = {"mu": {"x": rng.randn(4).astype(np.float32)}}
    kw = dict(means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2),
              num_classes=3)

    save_checkpoint(str(tmp_path / "sync"), 1, params, opt_state, **kw)

    writer = CheckpointWriter()
    writer.save(str(tmp_path / "async"), 1, params, opt_state, **kw)
    writer.close()

    p_sync, _ = load_checkpoint(str(tmp_path / "sync"), 1,
                                template={"params": params}, **kw)
    p_async, _ = load_checkpoint(str(tmp_path / "async"), 1,
                                 template={"params": params}, **kw)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        p_sync, p_async)
    # Round trip through the (un)normalization contract back to original.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        p_async, params)


def test_back_to_back_async_saves_serialize(tmp_path, rng):
    """The writer awaits the in-flight save before starting the next —
    both epochs land durable and loadable."""
    writer = CheckpointWriter()
    trees = []
    for epoch in (1, 2):
        t = _tree(rng)
        trees.append(t)
        writer.save(str(tmp_path / "ck"), epoch, t, num_classes=3)
    writer.close()
    for epoch, t in zip((1, 2), trees):
        loaded, _ = load_checkpoint(str(tmp_path / "ck"), epoch,
                                    template={"params": t}, num_classes=3)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6),
            loaded, t)
    writer.close()  # idempotent
