"""CLI --set override parsing (config.parse_cli_overrides) and the
bool-field guard in the dotted-override machinery."""

import pytest

from mx_rcnn_tpu.config import generate_config, parse_cli_overrides


def test_literals_bools_and_strings_parse():
    out = parse_cli_overrides([
        "train.batch_images=2",
        "train.lr=0.02",
        "image.pad_shape=(128,128)",
        "network.tensor_parallel=true",
        "network.use_mask=FALSE",
        "network.remat=off",
        "network.norm=group",
    ])
    assert out["train.batch_images"] == 2
    assert out["train.lr"] == 0.02
    assert out["image.pad_shape"] == (128, 128)
    assert out["network.tensor_parallel"] is True
    assert out["network.use_mask"] is False
    assert out["network.remat"] is False
    assert out["network.norm"] == "group"


def test_malformed_pair_raises():
    with pytest.raises(ValueError, match="KEY=VALUE"):
        parse_cli_overrides(["train.lr"])


def test_cli_bools_reach_config():
    cfg = generate_config(
        "resnet50", "synthetic",
        **parse_cli_overrides(["network.tensor_parallel=true"]))
    assert cfg.network.tensor_parallel is True


def test_string_on_bool_field_rejected():
    # A stray string must never land on a bool field (a truthy "false"
    # would silently ENABLE the feature it was meant to disable).
    with pytest.raises(ValueError, match="bool"):
        generate_config("resnet50", "synthetic",
                        **{"network.tensor_parallel": "maybe"})
