"""PascalVOC / COCODataset against the checked-in 2-image fixtures.

VERDICT round 1 flagged both dataset classes as never-executed (offline, no
data); tests/fixtures/mini_voc and mini_coco are tiny but REAL on-disk
datasets (actual JPEGs, VOC XML, COCO instances json incl. a crowd-RLE
annotation) so the parse → roidb → loader → eval paths run in CI.
"""

import os

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.datasets.coco import COCODataset
from mx_rcnn_tpu.data.datasets.pascal_voc import PascalVOC
from mx_rcnn_tpu.data.loader import AnchorLoader

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
VOC_ROOT = os.path.join(FIXTURES, "mini_voc/VOCdevkit")
COCO_ROOT = os.path.join(FIXTURES, "mini_coco")


# ---------------------------------------------------------------------------
# PASCAL VOC
# ---------------------------------------------------------------------------


@pytest.fixture
def voc():
    return PascalVOC("2007_minitest", root_path=FIXTURES,
                     dataset_path=VOC_ROOT)


def test_voc_index_and_roidb(voc):
    assert voc.image_index == ["000001", "000002"]
    roidb = voc._load_gt_roidb()
    assert len(roidb) == 2
    e1 = roidb[0]
    # Difficult person and non-VOC class are excluded from training boxes;
    # the dog stays, converted to 0-indexed coords.
    assert e1["boxes"].shape == (1, 4)
    np.testing.assert_allclose(e1["boxes"][0], [10, 8, 40, 38])
    assert voc.classes[e1["gt_classes"][0]] == "dog"
    # ... but kept for evaluation (difficult handling); the non-VOC class is
    # dropped entirely at parse.
    assert e1["all_boxes"].shape == (2, 4)
    assert e1["difficult"].tolist() == [False, True]
    assert e1["height"] == 48 and e1["width"] == 64


def test_voc_loader_reads_real_jpegs(voc):
    cfg = generate_config("resnet50", "PascalVOC", **{
        "image.pad_shape": (64, 64), "image.scales": ((48, 64),),
        "train.max_gt_boxes": 4, "train.flip": False,
    })
    roidb = voc._load_gt_roidb()
    loader = AnchorLoader(roidb, cfg, num_shards=1, shuffle=False, seed=0)
    batch = next(iter(loader))
    assert batch["image"].shape == (1, 64, 64, 3)
    assert batch["gt_valid"][0].sum() == 1
    # The dog rectangle is red-ish: the mean-subtracted red channel inside
    # the box must exceed the background's.
    img = batch["image"][0]
    assert img[20, 20, 0] > img[45, 2, 0]


def test_voc_eval_perfect_detections(voc, tmp_path):
    roidb = voc._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(voc.num_classes)]
    dog = voc.classes.index("dog")
    cat = voc.classes.index("cat")
    all_boxes[dog][0] = np.asarray([[10, 8, 40, 38, 0.9]], np.float32)
    all_boxes[cat][1] = np.asarray([[5, 5, 30, 30, 0.8]], np.float32)
    result = voc.evaluate_detections(all_boxes)
    assert result["dog"] == pytest.approx(1.0, abs=1e-4)
    assert result["cat"] == pytest.approx(1.0, abs=1e-4)
    # comp4 result files round-trip (reference write_pascal_results).
    voc.write_results(all_boxes, str(tmp_path))
    path = tmp_path / "comp4_det_minitest_dog.txt"
    assert path.exists()
    line = path.read_text().strip().split()
    assert line[0] == "000001" and float(line[2]) == 11.0  # 1-indexed


def test_voc_eval_difficult_not_counted(voc):
    """A detection on the difficult person neither scores nor hurts."""
    roidb = voc._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(voc.num_classes)]
    person = voc.classes.index("person")
    all_boxes[person][0] = np.asarray([[45, 5, 58, 42, 0.95]], np.float32)
    result = voc.evaluate_detections(all_boxes)
    # No non-difficult person gt anywhere: AP must be 0 (not negative /
    # crash), and the det must have been IGNORED rather than counted FP.
    assert result["person"] == 0.0


# ---------------------------------------------------------------------------
# COCO
# ---------------------------------------------------------------------------


@pytest.fixture
def coco():
    return COCODataset("minival", root_path=FIXTURES,
                       dataset_path=COCO_ROOT)


def test_coco_roidb(coco):
    roidb = coco._load_gt_roidb()
    assert coco.classes == ("__background__", "car", "dog")
    assert len(roidb) == 2
    e1, e2 = roidb
    # Crowd annotation excluded from training boxes.
    assert e1["boxes"].shape == (1, 4)
    np.testing.assert_allclose(e1["boxes"][0], [10, 10, 40, 40])
    assert e1["gt_classes"][0] == 1  # car → contiguous id 1 (cat id 3)
    # Out-of-bounds bbox is clipped into the image.
    assert e2["boxes"].shape == (2, 4)
    np.testing.assert_allclose(e2["boxes"][1], [0, 0, 6, 5])
    # Polygon segmentations ride along for the mask pipeline.
    assert e1["segmentations"][0] is not None
    assert len(e1["segmentations"]) == 1


def test_coco_loader_with_masks(coco):
    cfg = generate_config("resnet50_fpn_mask", "coco", **{
        "image.pad_shape": (64, 64), "image.scales": ((48, 64),),
        "train.max_gt_boxes": 4, "train.flip": False,
        "train.mask_gt_resolution": 28,
    })
    roidb = coco._load_gt_roidb()
    loader = AnchorLoader(roidb, cfg, num_shards=1, shuffle=False, seed=0)
    batches = list(loader)
    assert len(batches) == 2
    b = batches[0]
    assert b["gt_masks"].shape == (1, 4, 28, 28)
    # The car's polygon fills its whole box → its box-frame mask is ~all on.
    assert b["gt_masks"][0, 0].mean() > 0.9
    # Padding gt slots carry empty masks.
    assert b["gt_masks"][0, 3].sum() == 0


def test_coco_eval_perfect_detections(coco, tmp_path):
    roidb = coco._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(coco.num_classes)]
    # Perfect detections for all three non-crowd gts. Note the third matches
    # the ORIGINAL (unclipped) annotation bbox — COCO eval compares against
    # the json annotations, not the training-clipped roidb boxes.
    all_boxes[1][0] = np.asarray([[10, 10, 40, 40, 0.9]], np.float32)
    all_boxes[2][1] = np.asarray([[5, 20, 30, 55, 0.8]], np.float32)
    all_boxes[1][1] = np.asarray([[-3, -2, 6, 5, 0.7]], np.float32)
    out_json = str(tmp_path / "dets.json")
    stats = coco.evaluate_detections(all_boxes, out_json=out_json)
    assert stats["AP"] == pytest.approx(1.0, abs=1e-3), stats
    assert os.path.exists(out_json)


def test_coco_eval_false_positive_lowers_ap(coco):
    roidb = coco._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(coco.num_classes)]
    all_boxes[1][0] = np.asarray(
        [[10, 10, 40, 40, 0.9],
         [50, 2, 62, 12, 0.95]],  # confident FP in open space
        np.float32)
    all_boxes[2][1] = np.asarray([[5, 20, 30, 55, 0.8]], np.float32)
    all_boxes[1][1] = np.asarray([[0, 0, 6, 5, 0.7]], np.float32)
    stats = coco.evaluate_detections(all_boxes)
    assert stats["AP"] < 1.0


def test_coco_crowd_region_detection_ignored(coco):
    """A detection inside the crowd-RLE region must be IGNORED (matched to
    the crowd gt), not counted as a false positive — the maskApi crowd-IoU
    semantics flowing through eval."""
    roidb = coco._load_gt_roidb()
    n = len(roidb)

    def boxes_with_crowd_hit():
        all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                     for _ in range(coco.num_classes)]
        all_boxes[1][0] = np.asarray([[10, 10, 40, 40, 0.9]], np.float32)
        all_boxes[2][1] = np.asarray([[5, 20, 30, 55, 0.8]], np.float32)
        all_boxes[1][1] = np.asarray([[-3, -2, 6, 5, 0.7]], np.float32)
        # dog detection fully inside the crowd block (0,30)-(19,41) @img1
        all_boxes[2][0] = np.asarray([[2, 31, 17, 40, 0.85]], np.float32)
        return all_boxes

    stats = coco.evaluate_detections(boxes_with_crowd_hit())
    assert stats["AP"] == pytest.approx(1.0, abs=1e-3), stats


def test_coco_segm_eval_perfect_masks(coco, tmp_path):
    """evaluate_segmentations with pixel-perfect masks -> segm AP == 1."""
    from mx_rcnn_tpu import masks as M

    roidb = coco._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(coco.num_classes)]
    all_masks = [[[] for _ in range(n)] for _ in range(coco.num_classes)]

    def full_mask(poly, h, w):
        return M.fr_poly(poly, h, w)

    # img1 car: polygon rectangle (10,10)-(41,41) @ 48x64
    all_boxes[1][0] = np.asarray([[10, 10, 40, 40, 0.9]], np.float32)
    all_masks[1][0] = [full_mask(
        [[10.0, 10.0, 41.0, 10.0, 41.0, 41.0, 10.0, 41.0]], 48, 64)]
    # img2 dog: rectangle (5,20)-(31,56) @ 64x48
    all_boxes[2][1] = np.asarray([[5, 20, 30, 55, 0.8]], np.float32)
    all_masks[2][1] = [full_mask(
        [[5.0, 20.0, 31.0, 20.0, 31.0, 56.0, 5.0, 56.0]], 64, 48)]
    # img2 car: clipped corner box
    all_boxes[1][1] = np.asarray([[-3, -2, 6, 5, 0.7]], np.float32)
    all_masks[1][1] = [full_mask(
        [[0.0, 0.0, 6.0, 0.0, 6.0, 5.0, 0.0, 5.0]], 64, 48)]

    out_json = str(tmp_path / "segm.json")
    stats = coco.evaluate_segmentations(all_boxes, all_masks,
                                        out_json=out_json)
    assert stats["segm_AP"] == pytest.approx(1.0, abs=1e-3), stats
    assert stats["AP"] > 0.7  # bbox side still evaluated
    assert os.path.exists(out_json)
    # The written json is valid COCO segm results.
    import json as _json
    with open(out_json) as f:
        res = _json.load(f)
    assert all("segmentation" in r and "counts" in r["segmentation"]
               for r in res)


def test_coco_segm_eval_wrong_masks_score_low(coco):
    """Right boxes, wrong masks: bbox AP stays high, segm AP collapses."""
    from mx_rcnn_tpu import masks as M

    roidb = coco._load_gt_roidb()
    n = len(roidb)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(n)]
                 for _ in range(coco.num_classes)]
    all_masks = [[[] for _ in range(n)] for _ in range(coco.num_classes)]
    # Perfect boxes but masks covering only a sliver of each gt.
    sliver1 = np.zeros((48, 64), np.uint8); sliver1[10:12, 10:12] = 1
    sliver2 = np.zeros((64, 48), np.uint8); sliver2[20:22, 5:7] = 1
    sliver3 = np.zeros((64, 48), np.uint8); sliver3[0:1, 0:1] = 1
    all_boxes[1][0] = np.asarray([[10, 10, 40, 40, 0.9]], np.float32)
    all_masks[1][0] = [M.encode(sliver1)]
    all_boxes[2][1] = np.asarray([[5, 20, 30, 55, 0.8]], np.float32)
    all_masks[2][1] = [M.encode(sliver2)]
    all_boxes[1][1] = np.asarray([[-3, -2, 6, 5, 0.7]], np.float32)
    all_masks[1][1] = [M.encode(sliver3)]
    stats = coco.evaluate_segmentations(all_boxes, all_masks)
    assert stats["segm_AP"] < 0.2, stats
    assert stats["AP"] > 0.7


def test_gen_synthetic_coco_roundtrip(tmp_path):
    """tools/gen_synthetic_coco writes the documented COCO layout and the
    real COCODataset parses it (the r5 launch-rehearsal data path)."""
    pytest.importorskip("cv2")
    from mx_rcnn_tpu.tools.gen_synthetic_coco import generate_split

    root = str(tmp_path / "coco")
    info = generate_split(root, "val2017", num_images=4, seed=11)
    assert info["images"] == 4 and info["annotations"] >= 4
    ds = COCODataset("val2017", root_path=str(tmp_path), dataset_path=root)
    roidb = ds.gt_roidb()
    assert len(roidb) == 4
    assert ds.num_classes == 81  # full COCO category list declared
    for e in roidb:
        assert os.path.exists(e["image"])
        assert e["boxes"].shape[0] == e["gt_classes"].shape[0] >= 1
        assert (e["gt_classes"] >= 1).all() and (e["gt_classes"] <= 16).all()
    # Validate the RAW json (COCODataset clips boxes at parse time, so
    # roidb bounds checks would be tautological): every xywh bbox must
    # already lie within its image.
    import json as _json

    raw = _json.load(open(info["json"]))
    dims = {im["id"]: (im["width"], im["height"]) for im in raw["images"]}
    for ann in raw["annotations"]:
        w, h = dims[ann["image_id"]]
        x, y, bw, bh = ann["bbox"]
        assert 0 <= x and 0 <= y and x + bw <= w and y + bh <= h, ann


def test_gt_roidb_cache_distinguishes_dataset_paths(tmp_path):
    """Two COCO datasets sharing a split name at DIFFERENT paths must not
    reuse each other's roidb cache (r5 rehearsal bug: a small-copy set
    silently loaded the full set's pickle)."""
    pytest.importorskip("cv2")
    from mx_rcnn_tpu.tools.gen_synthetic_coco import generate_split

    a = str(tmp_path / "a"); b = str(tmp_path / "b")
    generate_split(a, "val2017", num_images=3, seed=1)
    generate_split(b, "val2017", num_images=5, seed=2)
    root = str(tmp_path)  # shared root_path -> shared cache dir
    ds_a = COCODataset("val2017", root_path=root, dataset_path=a)
    ds_b = COCODataset("val2017", root_path=root, dataset_path=b)
    assert len(ds_a.gt_roidb()) == 3
    assert len(ds_b.gt_roidb()) == 5  # not the cached 3-entry roidb
    assert len(ds_a.gt_roidb()) == 3  # both caches coexist


# ---------------------------------------------------------------------------
# loader shutdown (data/loader.py close/context-manager contract)
# ---------------------------------------------------------------------------


def _worker_threads():
    import threading

    return [t for t in threading.enumerate()
            if t.name.startswith("loader-worker") and t.is_alive()]


def _synthetic_loader(n=6):
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset

    cfg = generate_config("resnet50", "synthetic", **{
        "image.pad_shape": (64, 64), "image.scales": ((64, 64),),
        "train.batch_images": 1, "train.flip": False,
        "train.max_gt_boxes": 4})
    ds = SyntheticDataset("train", num_images=n, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    return AnchorLoader(ds.gt_roidb(), cfg, num_shards=1, seed=0)


def test_loader_close_joins_workers():
    """close() stops AND joins the prefetch pool: no loader worker thread
    survives, even when the epoch was abandoned mid-stream."""
    loader = _synthetic_loader()
    it = iter(loader)
    next(it)
    assert _worker_threads(), "prefetch pool never started"
    loader.close()
    assert not _worker_threads(), "worker threads survived close()"
    # close() is idempotent and the loader is reusable for a fresh epoch
    loader.close()
    assert sum(1 for _ in loader) == 6
    assert not _worker_threads()


def test_loader_iterator_disposal_joins_workers():
    """Disposing the epoch generator (the for-loop breaking out, or GC)
    runs the generator's finally — which closes AND joins the pool."""
    import gc

    loader = _synthetic_loader()
    it = iter(loader)
    next(it)
    del it
    gc.collect()
    assert not _worker_threads(), "worker threads survived disposal"


def test_loader_close_joins_overlapping_iterations():
    """Two live iterations over the same loader each own a pool; close()
    must join BOTH (a single-slot tracker would orphan the first)."""
    loader = _synthetic_loader()
    it1 = iter(loader)
    next(it1)
    it2 = iter(loader)
    next(it2)
    loader.close()
    assert not _worker_threads(), "a pool survived close()"


def test_loader_context_manager():
    with _synthetic_loader() as loader:
        for i, batch in enumerate(loader):
            assert np.isfinite(batch["image"]).all()
            if i == 1:
                break  # abandon mid-epoch; __exit__ must clean up
    assert not _worker_threads()
