"""DETR (models/detr.py): set loss with in-graph matching, forwards.

Stretch config 5 (with ViTDet). The reference has no transformer detectors
(SURVEY.md §3.2); semantics follow Carion et al. as documented.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import detr as D
from mx_rcnn_tpu.models import zoo


def tiny_cfg(**overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 1,
        "network.detr_queries": 20,
        "network.detr_hidden": 64,
        "network.detr_heads": 4,
        "network.detr_enc_layers": 2,
        "network.detr_dec_layers": 2,
        "network.norm": "group",
        "network.freeze_at": 0,
        "train.max_gt_boxes": 8,
    }
    base.update(overrides)
    return generate_config("detr_r50", "synthetic", **base)


def tiny_batch(rng):
    return {
        "image": rng.randn(1, 128, 128, 3).astype(np.float32),
        "im_info": np.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": np.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": np.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": np.asarray([[True, True] + [False] * 6]),
    }


def test_sine_position_encoding():
    pe = D.sine_position_encoding(4, 6, 64)
    assert pe.shape == (4, 6, 64)
    # Distinct positions get distinct encodings.
    flat = pe.reshape(-1, 64)
    assert len(np.unique(flat.round(5), axis=0)) == 24


def test_forward_train_matches_all_gt(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    # The auction must match exactly the 2 valid gt boxes.
    assert float(aux["num_fg"]) == 2.0


def test_aux_decoder_losses(rng):
    """Carion et al. §3.2 auxiliary decoding losses: per-layer matched set
    losses through SHARED heads — no extra params, every decoder layer
    supervised, total = sum over layers."""
    from dataclasses import replace

    cfg = tiny_cfg()  # detr_aux_loss defaults True
    cfg_no = cfg.with_updates(train=replace(cfg.train, detr_aux_loss=False))
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)

    loss_aux, m_aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    loss_no, m_no = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg_no)
    )(params, batch, jax.random.PRNGKey(1))

    # L=2 supervised layers: aux total strictly exceeds final-layer-only.
    assert float(loss_aux) > float(loss_no)
    # Metric slots report the final layer → identical across modes.
    np.testing.assert_allclose(float(m_aux["rcnn_cls_loss"]),
                               float(m_no["rcnn_cls_loss"]), rtol=1e-5)

    # Shared heads: aux mode invents no parameters (same tree, and dec0
    # now receives direct supervision -> nonzero grads).
    grads = jax.jit(jax.grad(
        lambda p: zoo.forward_train(model, p, batch,
                                    jax.random.PRNGKey(1), cfg)[0]))(params)
    assert jax.tree_util.tree_structure(grads) == \
        jax.tree_util.tree_structure(params)
    # (self_attn q/k at dec0 get structurally zero grads — the decoder
    # input is zeros, so layer-0 value vectors are identical; cross-attn
    # is where layer-0 supervision lands.)
    g0 = grads["params"]["dec0"]["cross_attn"]["q"]["kernel"]
    assert float(jnp.abs(g0).max()) > 0.0


def test_forward_test_contract(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    rois, rv, scores, boxes = jax.jit(
        lambda p, i, ii: zoo.forward_test(model, p, i, ii, cfg)
    )(params, batch["image"], batch["im_info"])
    q = cfg.network.detr_queries
    c = cfg.dataset.num_classes
    assert rois.shape == (1, q, 4)
    assert scores.shape == (1, q, c)
    assert boxes.shape == (1, q, 4 * c)
    s = np.asarray(scores)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)  # softmax rows


def test_no_gt_image(rng):
    """All-padding gt: loss is pure ∅ classification, finite."""
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    batch["gt_valid"] = np.zeros_like(batch["gt_valid"])
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert float(aux["num_fg"]) == 0.0


def test_loss_decreases_on_repeated_batch(rng):
    """A few SGD steps on one batch: the set loss must drop (matcher +
    gradients wired correctly end-to-end)."""
    import optax

    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    tx = optax.sgd(5e-4, momentum=0.9)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, key):
        (loss, aux), g = jax.value_and_grad(
            lambda p: zoo.forward_train(model, p, batch, key, cfg),
            has_aux=True)(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    key = jax.random.PRNGKey(2)
    for i in range(8):
        key, k = jax.random.split(key)
        params, opt, loss = step(params, opt, k)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_dp_mesh_step(rng):
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = tiny_cfg(**{"train.batch_images": 2})
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    mesh = create_mesh("2")
    step = make_train_step(model, cfg, mesh=mesh,
                           forward_fn=zoo.forward_train, donate=False)
    one = tiny_batch(rng)
    batch = {k: np.repeat(v, 2, axis=0) for k, v in one.items()}
    state, metrics = step(state, shard_batch(batch, mesh),
                          jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["TotalLoss"]))
