"""Multi-process (multi-host analog) DP: 2 processes × 4 CPU devices.

The reference's `dist_sync` KVStore has no testable analog in its repo
(SURVEY.md §5: multi-GPU is "tested" only by running it); here the
jax.distributed path (parallel/distributed.py) is exercised for real: two
spawned processes form one 8-device mesh, each feeds its local half of a
fixed global batch, and both must agree bit-for-bit on the loss and the
updated parameter checksum (the gradient all-reduce spans the process
boundary).
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.compile_heavy

WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
# Force CPU with 4 virtual devices BEFORE jax import; the axon
# sitecustomize is bypassed by PALLAS_AXON_POOL_IPS="" in the env.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

from mx_rcnn_tpu.parallel.distributed import maybe_initialize_distributed
maybe_initialize_distributed()

import jax, numpy as np
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.train.optimizer import build_optimizer
from mx_rcnn_tpu.train.step import create_train_state, make_train_step

cfg = generate_config("resnet50", "synthetic", **{
    "image.pad_shape": (64, 64),
    "network.anchor_scales": (2, 4),
    "train.rpn_pre_nms_top_n": 128, "train.rpn_post_nms_top_n": 32,
    "train.batch_rois": 16, "train.max_gt_boxes": 4,
    "train.batch_images": 1,
})
model = zoo.build_model(cfg)
params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
tx = build_optimizer(cfg, params, steps_per_epoch=10)
state = create_train_state(params, tx)
mesh = create_mesh("8")
step = make_train_step(model, cfg, mesh=mesh, donate=False)

# Global batch of 8 images, deterministic; this process slices its half.
rank = jax.process_index()
rs = np.random.RandomState(0)
g_img = rs.randn(8, 64, 64, 3).astype(np.float32)
gt = np.zeros((8, 4, 4), np.float32); gt[:, 0] = [8, 8, 40, 40]
valid = np.zeros((8, 4), bool); valid[:, 0] = True
cls = np.zeros((8, 4), np.int32); cls[:, 0] = 1
local = slice(rank * 4, rank * 4 + 4)
batch = {
    "image": g_img[local],
    "im_info": np.asarray([[64, 64, 1.0]] * 4, np.float32),
    "gt_boxes": gt[local], "gt_classes": cls[local],
    "gt_valid": valid[local],
}
state, metrics = step(state, shard_batch(batch, mesh), jax.random.PRNGKey(7))
loss = float(metrics["TotalLoss"])
ck = float(sum(jax.numpy.sum(jax.numpy.abs(l)).astype(jax.numpy.float64)
               for l in jax.tree.leaves(state.params)))
print(f"RESULT rank={rank} loss={loss:.8f} checksum={ck:.6f}", flush=True)
"""


def _run_two_process_workers(worker_src: str, tmp_path) -> dict:
    """Spawn 2 coordinated worker processes (MXRCNN_* env contract), wait,
    and return {rank: {key: value-string}} parsed from each RESULT line."""
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "PALLAS_AXON_POOL_IPS": "",  # skip the TPU claim
            "MXRCNN_COORDINATOR": f"127.0.0.1:{port}",
            "MXRCNN_NUM_PROCESSES": "2",
            "MXRCNN_PROCESS_ID": str(rank),
        })
        env.pop("JAX_PLATFORMS", None)  # worker sets its own
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    results = {}
    for out, p in zip(outs, procs):
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][0]
        kv = dict(part.split("=") for part in line.split()[1:])
        results[int(kv.pop("rank"))] = kv
    assert set(results) == {0, 1}
    return results


@pytest.mark.slow
def test_two_process_dp(tmp_path):
    results = _run_two_process_workers(WORKER, tmp_path)
    # Replicated state: both processes computed the SAME loss and params.
    assert results[0] == results[1], results


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


TP_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

from mx_rcnn_tpu.parallel.distributed import maybe_initialize_distributed
maybe_initialize_distributed()

import jax, numpy as np
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.parallel.partition import shard_train_state, tp_param_specs
from mx_rcnn_tpu.train.optimizer import build_optimizer
from mx_rcnn_tpu.train.step import create_train_state, make_train_step

cfg = generate_config("detr_r50", "synthetic", **{
    "image.pad_shape": (64, 64),
    "network.detr_queries": 10,
    "network.detr_hidden": 32,
    "network.detr_heads": 2,
    "network.detr_enc_layers": 1,
    "network.detr_dec_layers": 1,
    "network.norm": "group",
    "network.freeze_at": 0,
    "train.compute_dtype": "f32",
    "network.tensor_parallel": True,
    "train.max_gt_boxes": 4,
    "train.batch_images": 1,
})
model = zoo.build_model(cfg)
params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
tx = build_optimizer(cfg, params, steps_per_epoch=10)
state = create_train_state(params, tx)
# (data=4, model=2): the DP gradient psum crosses the process boundary,
# the Megatron TP collectives stay intra-process (the ICI-like axis).
mesh = create_mesh("4x2")
specs = tp_param_specs(state.params)
state = shard_train_state(state, mesh, specs)
step = make_train_step(model, cfg, mesh=mesh, donate=False,
                       forward_fn=zoo.forward_train, param_specs=specs)

rank = jax.process_index()
rs = np.random.RandomState(0)
g_img = rs.randn(4, 64, 64, 3).astype(np.float32)
gt = np.zeros((4, 4, 4), np.float32); gt[:, 0] = [8, 8, 40, 40]
valid = np.zeros((4, 4), bool); valid[:, 0] = True
cls = np.zeros((4, 4), np.int32); cls[:, 0] = 1
local = slice(rank * 2, rank * 2 + 2)
batch = {
    "image": g_img[local],
    "im_info": np.asarray([[64, 64, 1.0]] * 2, np.float32),
    "gt_boxes": gt[local], "gt_classes": cls[local],
    "gt_valid": valid[local],
}
state, metrics = step(state, shard_batch(batch, mesh), jax.random.PRNGKey(7))
loss = float(metrics["TotalLoss"])
ck = float(sum(jax.numpy.sum(jax.numpy.abs(l)).astype(jax.numpy.float64)
               for l in jax.tree.leaves(state.params)))
n_sharded = sum(1 for l in jax.tree.leaves(state.params)
                if not l.sharding.is_fully_replicated)
print(f"RESULT rank={rank} loss={loss:.8f} checksum={ck:.6f} "
      f"sharded={n_sharded}", flush=True)
"""


@pytest.mark.slow
def test_two_process_dp_tp(tmp_path):
    """DP x TP across a process boundary: 2 processes x 4 devices form a
    (4, 2) mesh; Megatron-sharded DETR weights, gradient psum spanning
    the processes. Both ranks must agree bit-for-bit."""
    results = _run_two_process_workers(TP_WORKER, tmp_path)
    assert results[0] == results[1], results
    assert int(results[0]["sharded"]) > 0, "no TP-sharded leaves"


PP_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

from mx_rcnn_tpu.parallel.distributed import maybe_initialize_distributed
maybe_initialize_distributed()

import jax, numpy as np
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.train.optimizer import build_optimizer
from mx_rcnn_tpu.train.step import create_train_state, make_train_step

cfg = generate_config("vitdet_b", "synthetic", **{
    "image.pad_shape": (64, 64),
    "network.vit_dim": 32,
    "network.vit_depth": 2,
    "network.vit_heads": 2,
    "network.vit_window": 4,
    "train.compute_dtype": "f32",
    "network.pp_stages": 2,
    "network.anchor_scales": (2, 4),
    "train.fpn_rpn_pre_nms_per_level": 64,
    "train.rpn_post_nms_top_n": 32,
    "train.batch_rois": 16,
    "train.max_gt_boxes": 4,
    "train.batch_images": 2,
})
# INTERLEAVE the global device list so the (4, 2) mesh's model axis
# pairs one device from EACH process: the GPipe ppermute ring hops
# across the process boundary (cross-"host" pipeline), while the data
# axis stays local per process.
devs = jax.devices()
order = [devs[i + 4 * p] for i in range(4) for p in range(2)]
mesh = create_mesh("4x2", order)
# The point of this worker: every model-axis pair must span BOTH
# processes, or the ppermute ring never crosses a process boundary and
# the test passes vacuously.
for row in mesh.devices:
    assert {d.process_index for d in row} == {0, 1}, mesh.devices
model = zoo.build_model(cfg, mesh=mesh)
params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
tx = build_optimizer(cfg, params, steps_per_epoch=10)
state = create_train_state(params, tx)
step = make_train_step(model, cfg, mesh=mesh, donate=False,
                       forward_fn=zoo.forward_train)

# With the interleaved order every data row spans BOTH processes (each
# holds one model-half of every row), so process-local data for the
# P("data") sharding is the FULL global batch — each process feeds all
# 8 images and make_array_from_process_local_data takes the rows its
# devices cover.
# 8 global images: 2 per data shard, and each 4-image microbatch still
# divides over the 4-way data axis (pipeline_apply guard).
rank = jax.process_index()
rs = np.random.RandomState(0)
g_img = rs.randn(8, 64, 64, 3).astype(np.float32)
gt = np.zeros((8, 4, 4), np.float32); gt[:, 0] = [8, 8, 40, 40]
valid = np.zeros((8, 4), bool); valid[:, 0] = True
cls = np.zeros((8, 4), np.int32); cls[:, 0] = 1
batch = {
    "image": g_img,
    "im_info": np.asarray([[64, 64, 1.0]] * 8, np.float32),
    "gt_boxes": gt, "gt_classes": cls,
    "gt_valid": valid,
}
state, metrics = step(state, shard_batch(batch, mesh), jax.random.PRNGKey(7))
loss = float(metrics["TotalLoss"])
ck = float(sum(jax.numpy.sum(jax.numpy.abs(l)).astype(jax.numpy.float64)
               for l in jax.tree.leaves(state.params)))
print(f"RESULT rank={rank} loss={loss:.8f} checksum={ck:.6f}", flush=True)
"""


@pytest.mark.slow
def test_two_process_dp_pp(tmp_path):
    """DP x PP with the pipeline ring CROSSING the process boundary: the
    mesh model axis pairs one device from each process (interleaved
    order), so every GPipe ppermute hop is a cross-process transfer.
    Ranks must agree bit-for-bit."""
    results = _run_two_process_workers(PP_WORKER, tmp_path)
    assert results[0] == results[1], results
