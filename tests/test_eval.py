"""Eval-protocol tests: VOC AP and the in-repo COCO eval on hand-checked cases
(SURVEY.md §8 'Hard parts' #4 — validate COCO matching on small cases)."""

import numpy as np

from mx_rcnn_tpu.evaluation.coco_eval import COCOEval, bbox_iou_xywh
from mx_rcnn_tpu.evaluation.voc_eval import eval_class, voc_ap


def det(img, x, y, w, h, score, cat=1):
    return {"image_id": img, "category_id": cat,
            "bbox": [x, y, w, h], "score": score}


def gt(img, x, y, w, h, cat=1, crowd=0, ann_id=0):
    return {"id": ann_id, "image_id": img, "category_id": cat,
            "bbox": [x, y, w, h], "area": w * h, "iscrowd": crowd}


def make_dataset(gts, num_images=2, cats=(1,)):
    return {
        "images": [{"id": i, "width": 640, "height": 480}
                   for i in range(num_images)],
        "categories": [{"id": c, "name": f"c{c}"} for c in cats],
        "annotations": gts,
    }


class TestVocAp:
    def test_perfect_detection(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50]], float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        assert eval_class(gt_by_image, det_by_image) == 1.0

    def test_miss_halves_recall(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50], [100, 100, 150, 150]],
                                   float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        ap = eval_class(gt_by_image, det_by_image)
        assert abs(ap - 0.5) < 1e-6

    def test_duplicate_is_fp(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50]], float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9],
                                     [11, 11, 51, 51, 0.8]], float)}
        # AP unchanged (dup ranks below the TP) but precision tail dips.
        ap = eval_class(gt_by_image, det_by_image)
        assert abs(ap - 1.0) < 1e-6

    def test_difficult_excluded(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50], [100, 100, 150, 150]],
                                   float)}
        diff = {0: np.array([False, True])}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        assert eval_class(gt_by_image, det_by_image, diff) == 1.0

    def test_07_metric_differs(self):
        rec = np.array([0.5])
        prec = np.array([1.0])
        assert abs(voc_ap(rec, prec, use_07_metric=True) - 6 / 11) < 1e-6
        assert abs(voc_ap(rec, prec, use_07_metric=False) - 0.5) < 1e-6


class TestCocoEval:
    def test_iou_xywh(self):
        d = np.array([[0, 0, 10, 10]], float)
        g = np.array([[0, 0, 10, 10], [5, 0, 10, 10]], float)
        iou = bbox_iou_xywh(d, g, np.array([False, False]))
        assert abs(iou[0, 0] - 1.0) < 1e-9
        assert abs(iou[0, 1] - 50 / 150) < 1e-9

    def test_crowd_iou_is_iof(self):
        d = np.array([[0, 0, 10, 10]], float)
        g = np.array([[0, 0, 100, 100]], float)
        iou = bbox_iou_xywh(d, g, np.array([True]))
        assert abs(iou[0, 0] - 1.0) < 1e-9  # det fully inside crowd

    def test_perfect_single(self):
        gts = [gt(0, 10, 10, 40, 40, ann_id=1)]
        dets = [det(0, 10, 10, 40, 40, 0.9)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["AP"] - 1.0) < 1e-6
        assert abs(stats["AP50"] - 1.0) < 1e-6

    def test_loose_box_fails_high_ious(self):
        # IoU ≈ 0.6 box: TP at thresholds ≤0.6, FP above.
        gts = [gt(0, 0, 0, 100, 100, ann_id=1)]
        dets = [det(0, 0, 0, 80, 100, 0.9)]  # IoU = 0.8
        stats = COCOEval(make_dataset(gts), dets).summarize()
        # AP = mean over thresholds: 1.0 for thr <= 0.8 (7 of 10), 0 above.
        assert abs(stats["AP"] - 0.7) < 1e-6
        assert abs(stats["AP50"] - 1.0) < 1e-6
        assert abs(stats["AP75"] - 1.0) < 1e-6

    def test_crowd_match_not_fp(self):
        # A det matching only a crowd region is ignored, not an FP; the
        # other det is a clean TP -> AP stays 1.
        gts = [gt(0, 10, 10, 40, 40, ann_id=1),
               gt(0, 200, 200, 100, 100, crowd=1, ann_id=2)]
        dets = [det(0, 10, 10, 40, 40, 0.9),
                det(0, 210, 210, 50, 50, 0.8)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["AP"] - 1.0) < 1e-6

    def test_unmatched_det_is_fp(self):
        gts = [gt(0, 10, 10, 40, 40, ann_id=1)]
        dets = [det(0, 10, 10, 40, 40, 0.9),
                det(0, 300, 300, 40, 40, 0.95)]  # higher-ranked FP
        stats = COCOEval(make_dataset(gts), dets).summarize()
        # Precision at recall>0 is 0.5 everywhere after the FP outranks the TP.
        assert stats["AP"] < 0.6

    def test_area_ranges(self):
        gts = [gt(0, 0, 0, 10, 10, ann_id=1),       # small (100 px²)
               gt(0, 100, 100, 200, 200, ann_id=2)]  # large
        dets = [det(0, 0, 0, 10, 10, 0.9),
                det(0, 100, 100, 200, 200, 0.8)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["APs"] - 1.0) < 1e-6
        assert abs(stats["APl"] - 1.0) < 1e-6
        assert stats["APm"] == -1.0  # no medium gt

    def test_maxdets_cap(self):
        gts = [gt(0, i * 30, 0, 20, 20, ann_id=i) for i in range(5)]
        dets = [det(0, i * 30, 0, 20, 20, 0.5 + 0.01 * i) for i in range(5)]
        ev = COCOEval(make_dataset(gts), dets, max_dets=(1, 10, 100))
        ev.accumulate()
        ap_1 = ev._ap(max_det=1)
        ap_100 = ev._ap(max_det=100)
        assert ap_100 > ap_1  # capping to 1 det loses recall


class TestMatcherDifferential:
    """The vectorized greedy matcher must reproduce the scalar pycocotools
    scan exactly (per-threshold availability, non-ignore preference,
    >= tie update → last-tie argmax)."""

    @staticmethod
    def _scalar_match(ious, iscrowd, gt_ignore, iou_thrs):
        T, (D, G) = len(iou_thrs), ious.shape
        dt_match = np.zeros((T, D), bool)
        dt_ignore = np.zeros((T, D), bool)
        gt_match = np.zeros((T, G), bool)
        for t, thr in enumerate(iou_thrs):
            for di in range(D):
                best_iou = min(thr, 1 - 1e-10)
                m = -1
                for gi in range(G):
                    if gt_match[t, gi] and not iscrowd[gi]:
                        continue
                    if m > -1 and not gt_ignore[m] and gt_ignore[gi]:
                        break
                    if ious[di, gi] < best_iou:
                        continue
                    best_iou = ious[di, gi]
                    m = gi
                if m == -1:
                    continue
                dt_match[t, di] = True
                dt_ignore[t, di] = gt_ignore[m]
                gt_match[t, m] = True
        return dt_match, dt_ignore

    def test_random_cells_match_scalar(self):
        rs = np.random.RandomState(7)
        for trial in range(50):
            d = rs.randint(0, 12)
            g = rs.randint(0, 8)
            gts, dets = [], []
            for i in range(g):
                x, y = rs.uniform(0, 80, 2)
                w, h = rs.uniform(5, 60, 2)
                gts.append(gt(0, x, y, w, h, crowd=int(rs.rand() < 0.25),
                              ann_id=i))
            for _ in range(d):
                x, y = rs.uniform(0, 80, 2)
                w, h = rs.uniform(5, 60, 2)
                dets.append(det(0, x, y, w, h, rs.rand()))
            # quantize IoUs so exact ties actually occur
            ev = COCOEval(make_dataset(gts), dets)
            dts = sorted(dets, key=lambda r: -r["score"])
            iscrowd = np.array([bool(x["iscrowd"]) for x in gts], bool)
            gt_areas = [x["area"] for x in gts]
            gb = np.array([x["bbox"] for x in gts], np.float64).reshape(-1, 4)
            db = np.array([x["bbox"] for x in dts], np.float64).reshape(-1, 4)
            ious = (bbox_iou_xywh(db, gb, iscrowd) if d and g
                    else np.zeros((d, g)))
            ious = np.round(ious, 1)  # force ties
            from mx_rcnn_tpu.evaluation.coco_eval import AREA_RANGES, IOU_THRS
            for rng in AREA_RANGES.values():
                res = ev._evaluate_img(gts, gt_areas, iscrowd, dts, ious, rng)
                # rebuild the sorted-order inputs _evaluate_img used
                gt_ign = np.array([
                    bool(x.get("iscrowd", 0))
                    or not (rng[0] <= a < rng[1])
                    for x, a in zip(gts, gt_areas)], bool)
                order = np.argsort(gt_ign, kind="stable")
                sm, si = self._scalar_match(
                    ious[:, order] if ious.size else ious,
                    iscrowd[order], gt_ign[order], IOU_THRS)
                d_areas = db[:, 2] * db[:, 3]
                d_out = (d_areas < rng[0]) | (d_areas >= rng[1])
                si = si | (~sm & d_out[None, :])
                np.testing.assert_array_equal(res["dt_match"], sm)
                np.testing.assert_array_equal(res["dt_ignore"], si)
