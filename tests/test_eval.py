"""Eval-protocol tests: VOC AP and the in-repo COCO eval on hand-checked cases
(SURVEY.md §8 'Hard parts' #4 — validate COCO matching on small cases)."""

import numpy as np

from mx_rcnn_tpu.evaluation.coco_eval import COCOEval, bbox_iou_xywh
from mx_rcnn_tpu.evaluation.voc_eval import eval_class, voc_ap


def det(img, x, y, w, h, score, cat=1):
    return {"image_id": img, "category_id": cat,
            "bbox": [x, y, w, h], "score": score}


def gt(img, x, y, w, h, cat=1, crowd=0, ann_id=0):
    return {"id": ann_id, "image_id": img, "category_id": cat,
            "bbox": [x, y, w, h], "area": w * h, "iscrowd": crowd}


def make_dataset(gts, num_images=2, cats=(1,)):
    return {
        "images": [{"id": i, "width": 640, "height": 480}
                   for i in range(num_images)],
        "categories": [{"id": c, "name": f"c{c}"} for c in cats],
        "annotations": gts,
    }


class TestVocAp:
    def test_perfect_detection(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50]], float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        assert eval_class(gt_by_image, det_by_image) == 1.0

    def test_miss_halves_recall(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50], [100, 100, 150, 150]],
                                   float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        ap = eval_class(gt_by_image, det_by_image)
        assert abs(ap - 0.5) < 1e-6

    def test_duplicate_is_fp(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50]], float)}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9],
                                     [11, 11, 51, 51, 0.8]], float)}
        # AP unchanged (dup ranks below the TP) but precision tail dips.
        ap = eval_class(gt_by_image, det_by_image)
        assert abs(ap - 1.0) < 1e-6

    def test_difficult_excluded(self):
        gt_by_image = {0: np.array([[10, 10, 50, 50], [100, 100, 150, 150]],
                                   float)}
        diff = {0: np.array([False, True])}
        det_by_image = {0: np.array([[10, 10, 50, 50, 0.9]], float)}
        assert eval_class(gt_by_image, det_by_image, diff) == 1.0

    def test_07_metric_differs(self):
        rec = np.array([0.5])
        prec = np.array([1.0])
        assert abs(voc_ap(rec, prec, use_07_metric=True) - 6 / 11) < 1e-6
        assert abs(voc_ap(rec, prec, use_07_metric=False) - 0.5) < 1e-6


class TestCocoEval:
    def test_iou_xywh(self):
        d = np.array([[0, 0, 10, 10]], float)
        g = np.array([[0, 0, 10, 10], [5, 0, 10, 10]], float)
        iou = bbox_iou_xywh(d, g, np.array([False, False]))
        assert abs(iou[0, 0] - 1.0) < 1e-9
        assert abs(iou[0, 1] - 50 / 150) < 1e-9

    def test_crowd_iou_is_iof(self):
        d = np.array([[0, 0, 10, 10]], float)
        g = np.array([[0, 0, 100, 100]], float)
        iou = bbox_iou_xywh(d, g, np.array([True]))
        assert abs(iou[0, 0] - 1.0) < 1e-9  # det fully inside crowd

    def test_perfect_single(self):
        gts = [gt(0, 10, 10, 40, 40, ann_id=1)]
        dets = [det(0, 10, 10, 40, 40, 0.9)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["AP"] - 1.0) < 1e-6
        assert abs(stats["AP50"] - 1.0) < 1e-6

    def test_loose_box_fails_high_ious(self):
        # IoU ≈ 0.6 box: TP at thresholds ≤0.6, FP above.
        gts = [gt(0, 0, 0, 100, 100, ann_id=1)]
        dets = [det(0, 0, 0, 80, 100, 0.9)]  # IoU = 0.8
        stats = COCOEval(make_dataset(gts), dets).summarize()
        # AP = mean over thresholds: 1.0 for thr <= 0.8 (7 of 10), 0 above.
        assert abs(stats["AP"] - 0.7) < 1e-6
        assert abs(stats["AP50"] - 1.0) < 1e-6
        assert abs(stats["AP75"] - 1.0) < 1e-6

    def test_crowd_match_not_fp(self):
        # A det matching only a crowd region is ignored, not an FP; the
        # other det is a clean TP -> AP stays 1.
        gts = [gt(0, 10, 10, 40, 40, ann_id=1),
               gt(0, 200, 200, 100, 100, crowd=1, ann_id=2)]
        dets = [det(0, 10, 10, 40, 40, 0.9),
                det(0, 210, 210, 50, 50, 0.8)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["AP"] - 1.0) < 1e-6

    def test_unmatched_det_is_fp(self):
        gts = [gt(0, 10, 10, 40, 40, ann_id=1)]
        dets = [det(0, 10, 10, 40, 40, 0.9),
                det(0, 300, 300, 40, 40, 0.95)]  # higher-ranked FP
        stats = COCOEval(make_dataset(gts), dets).summarize()
        # Precision at recall>0 is 0.5 everywhere after the FP outranks the TP.
        assert stats["AP"] < 0.6

    def test_area_ranges(self):
        gts = [gt(0, 0, 0, 10, 10, ann_id=1),       # small (100 px²)
               gt(0, 100, 100, 200, 200, ann_id=2)]  # large
        dets = [det(0, 0, 0, 10, 10, 0.9),
                det(0, 100, 100, 200, 200, 0.8)]
        stats = COCOEval(make_dataset(gts), dets).summarize()
        assert abs(stats["APs"] - 1.0) < 1e-6
        assert abs(stats["APl"] - 1.0) < 1e-6
        assert stats["APm"] == -1.0  # no medium gt

    def test_maxdets_cap(self):
        gts = [gt(0, i * 30, 0, 20, 20, ann_id=i) for i in range(5)]
        dets = [det(0, i * 30, 0, 20, 20, 0.5 + 0.01 * i) for i in range(5)]
        ev = COCOEval(make_dataset(gts), dets, max_dets=(1, 10, 100))
        ev.accumulate()
        ap_1 = ev._ap(max_det=1)
        ap_100 = ev._ap(max_det=100)
        assert ap_100 > ap_1  # capping to 1 det loses recall
