"""graftfeed gates — input-plane fault tolerance (data/feedguard.py).

Three layers, cheapest first:

- FeedGuard unit gates with injectable sleep/clock: classification,
  retry-under-deadline, deterministic quarantine + persistence/reapply,
  the cap abort;
- loader-level chaos gates on a real AnchorLoader (no jax, no fit):
  transient-IO retry leaves the stream bit-identical, a chaos-killed
  prefetch worker is resurrected at its queue position, a hang raises
  DataStallError within data.wait_deadline_s, close() stays idempotent;
- fit-level chaos gates riding tests/_resilience_driver.py: a corrupt
  record quarantines and the run COMPLETES; SIGTERM mid-quarantine +
  ``--resume auto`` is BIT-exact vs an uninterrupted chaos run (tree
  and flat); a hang crashes with a flight dump whose stall event names
  data-wait; the default quarantine cap aborts loudly.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import DataConfig
from mx_rcnn_tpu.data.feedguard import (
    DataStallError,
    DataWorkerError,
    FeedGuard,
    QuarantineExceededError,
    classify_record_error,
)
from mx_rcnn_tpu.data.loader import AnchorLoader
from mx_rcnn_tpu.obs import report
from mx_rcnn_tpu.obs.events import EventLog
from mx_rcnn_tpu.resilience import PreemptionExit, chaos

import _resilience_driver as driver

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    """No injection leaks between tests (or in from the outer env)."""
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _dcfg(**kw):
    return dataclasses.replace(DataConfig(), **kw)


def _roidb(n=6):
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset

    ds = SyntheticDataset("train", num_images=n, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    return ds.gt_roidb()


def _batches(loader):
    loader.set_epoch(0)
    try:
        return list(iter(loader))
    finally:
        loader.close()


def _assert_streams_equal(a, b):
    assert len(a) == len(b)
    for ba, bb in zip(a, b):
        assert set(ba) == set(bb)
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k], err_msg=k)


# ---------------------------------------------------------------------------
# classification + retry (FeedGuard units, injectable clock)
# ---------------------------------------------------------------------------

def test_classify_record_error_errno_and_markers():
    assert classify_record_error(OSError(errno.EIO, "x")) == "transient"
    assert classify_record_error(
        OSError(errno.ETIMEDOUT, "x")) == "transient"
    assert classify_record_error(OSError(errno.ESTALE, "x")) == "transient"
    # wrapped decoder/mmap flake signatures
    assert classify_record_error(
        ValueError("truncated read at offset 4096")) == "transient"
    assert classify_record_error(
        RuntimeError("mount: Stale file handle")) == "transient"
    # corruption is permanent — so is a generic OSError (ENOENT is a
    # missing file, not a flake)
    assert classify_record_error(
        ValueError("corrupt JPEG data: bad Huffman code")) == "permanent"
    assert classify_record_error(OSError(errno.ENOENT, "x")) == "permanent"


def test_retry_rides_transient_flakes_then_succeeds(tmp_path):
    """Two EIO flakes back off (jittered, bounded) and the record loads;
    nothing is quarantined; each retry leaves a typed ``data`` event."""
    elog = EventLog(str(tmp_path / "events_p0.jsonl"))
    sleeps = []
    fails = {"left": 2}

    def load(i):
        if fails["left"]:
            fails["left"] -= 1
            raise OSError(errno.EIO, "Input/output error")
        return ("record", i)

    g = FeedGuard(_dcfg(record_backoff_base_s=0.05,
                        record_backoff_max_s=0.2),
                  n_records=10, elog=elog, sleep=sleeps.append,
                  clock=lambda: 0.0)
    assert g.load(load, 3) == (("record", 3), 3)
    assert g.quarantined_count == 0 and g.retry_count == 2
    assert len(sleeps) == 2
    assert 0.05 <= sleeps[0] <= 0.05 * 1.25   # base, +25% jitter max
    assert 0.1 <= sleeps[1] <= 0.1 * 1.25     # doubled
    elog.close()
    retries = [e for e in report.load_events(str(tmp_path))
               if e["type"] == "data" and e["kind"] == "retry"]
    assert len(retries) == 2
    assert retries[0]["record"] == 3 and retries[0]["attempt"] == 1
    assert "Input/output error" in retries[0]["error"]


def test_retry_deadline_reclassifies_as_permanent():
    """A record still transiently failing past data.record_deadline_s is
    quarantined (the give-up OSError chains the original flake)."""
    t = {"now": 0.0}

    def clock():
        t["now"] += 40.0   # two attempts cross the 60s deadline
        return t["now"]

    def load(i):
        if i == 2:
            raise OSError(errno.ETIMEDOUT, "read timed out")
        return ("record", i)

    g = FeedGuard(_dcfg(record_deadline_s=60.0,
                        quarantine_max_fraction=0.5),
                  n_records=10, seed=0, sleep=lambda s: None, clock=clock)
    result, actual = g.load(load, 2)
    assert actual != 2 and result == ("record", actual)
    assert g.quarantined_count == 1


def test_retry_disabled_propagates_raw_transient():
    """data.record_deadline_s=0 restores pre-graftfeed behavior for
    transient IO: the raw OSError stays loud, nothing is quarantined."""
    g = FeedGuard(_dcfg(record_deadline_s=0.0), n_records=10,
                  sleep=lambda s: None)
    with pytest.raises(OSError) as ei:
        g.load(lambda i: (_ for _ in ()).throw(
            OSError(errno.EIO, "Input/output error")), 1)
    assert ei.value.errno == errno.EIO
    assert g.quarantined_count == 0


# ---------------------------------------------------------------------------
# quarantine: determinism, persistence, reapply, the cap
# ---------------------------------------------------------------------------

def test_quarantine_replacement_is_pure_and_avoids_set(tmp_path):
    """The substitute is f(seed, epoch, record): two independent guards
    draw the SAME replacement, and a replacement never lands on a
    quarantined record (chained corruption re-quarantines)."""
    def corrupt(bad):
        def load(i):
            if i in bad:
                raise ValueError(f"corrupt JPEG data: record {i}")
            return i
        return load

    def fresh():
        g = FeedGuard(_dcfg(quarantine_max_fraction=0.9), n_records=20,
                      seed=7, sleep=lambda s: None)
        g.set_epoch(3)
        return g

    g1, g2 = fresh(), fresh()
    r1 = g1.load(corrupt({4}), 4)
    assert r1 == g2.load(corrupt({4}), 4)  # pure draw, no shared rng
    # chained: the replacement for 4 is ALSO corrupt -> both quarantined,
    # final substitute avoids both
    g3 = fresh()
    result, actual = g3.load(corrupt({4, r1[1]}), 4)
    assert actual not in (4, r1[1]) and result == actual
    assert g3.quarantined_count == 2
    # a later load of a known-quarantined record pre-resolves without
    # re-attempting (the load_fn would raise if called on 4 again)
    assert g3.resolve(4) not in (4, r1[1])


def test_quarantine_persists_and_reapplies_on_resume(tmp_path):
    """quarantine.jsonl round-trip: the interrupted run's file re-arms a
    resume=True guard (quarantine_applied event), so substitutions
    replay without re-discovery; a fresh (non-resume) guard ignores
    the stale file."""
    path = str(tmp_path / "quarantine.jsonl")
    elog = EventLog(str(tmp_path / "events_p0.jsonl"))
    g = FeedGuard(_dcfg(quarantine_max_fraction=0.9), n_records=20,
                  seed=1, elog=elog, quarantine_path=path,
                  sleep=lambda s: None)
    _, actual = g.load(lambda i: i if i != 5 else (_ for _ in ()).throw(
        ValueError("corrupt JPEG data")), 5)
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 1
    assert lines[0]["record"] == 5
    assert lines[0]["replacement"] == actual
    assert "corrupt JPEG" in lines[0]["reason"]

    g_resumed = FeedGuard(_dcfg(), n_records=20, seed=1, elog=elog,
                          quarantine_path=path, resume=True,
                          sleep=lambda s: None)
    assert g_resumed.quarantined_count == 1
    assert g_resumed.resolve(5) == actual  # same pure draw, no load
    g_fresh = FeedGuard(_dcfg(), n_records=20, seed=1,
                        quarantine_path=path, sleep=lambda s: None)
    assert g_fresh.quarantined_count == 0
    elog.close()
    applied = [e for e in report.load_events(str(tmp_path))
               if e["type"] == "data"
               and e["kind"] == "quarantine_applied"]
    assert len(applied) == 1 and applied[0]["count"] == 1


def test_quarantine_cap_aborts_loudly(tmp_path):
    """Crossing data.quarantine_max_fraction raises (not substitutes) —
    with the evidence persisted FIRST and a quarantine_cap event."""
    path = str(tmp_path / "quarantine.jsonl")
    elog = EventLog(str(tmp_path / "events_p0.jsonl"))
    g = FeedGuard(_dcfg(quarantine_max_fraction=0.25), n_records=4,
                  elog=elog, quarantine_path=path, sleep=lambda s: None)
    g.load(lambda i: i if i != 0 else (_ for _ in ()).throw(
        ValueError("corrupt JPEG data")), 0)   # 1/4 == cap: allowed
    with pytest.raises(QuarantineExceededError) as ei:
        g.load(lambda i: i if i != 1 else (_ for _ in ()).throw(
            ValueError("corrupt JPEG data")), 1)   # 2/4 > cap
    assert "quarantine_max_fraction" in str(ei.value)
    assert len(open(path).readlines()) == 2  # persisted before the abort
    elog.close()
    kinds = [e["kind"] for e in report.load_events(str(tmp_path))
             if e["type"] == "data"]
    assert kinds.count("quarantine") == 2
    assert kinds.count("quarantine_cap") == 1


# ---------------------------------------------------------------------------
# chaos keys (resilience/chaos.py data sites)
# ---------------------------------------------------------------------------

def test_chaos_parse_data_keys_and_validation():
    spec = chaos.parse("data_corrupt_at=1:3 data_io_error_at=0:2:2 "
                       "data_hang_at=1:0 data_worker_die_at=1")
    assert spec.data_corrupt_at == "1:3"
    assert spec.data_io_error_at == "0:2:2"
    assert spec.data_hang_at == "1:0" and spec.data_worker_die_at == 1
    assert spec.active
    with pytest.raises(ValueError, match="data_corrupt_at"):
        chaos.parse("data_corrupt_at=1:2:3")   # E:I, not E:I:N
    with pytest.raises(ValueError, match="data_io_error_at"):
        chaos.parse("data_io_error_at=0:2")    # E:I:N, not E:I


def test_chaos_data_hooks_fire_at_their_keys():
    chaos.reset()
    spec = chaos.parse("data_corrupt_at=1:3 data_io_error_at=0:2:2 "
                       "data_worker_die_at=1")
    spec.maybe_data_corrupt(0, 3)  # wrong epoch: inert
    spec.maybe_data_corrupt(1, 2)  # wrong record: inert
    with pytest.raises(ValueError, match="corrupt JPEG"):
        spec.maybe_data_corrupt(1, 3)
    with pytest.raises(ValueError, match="corrupt JPEG"):
        spec.maybe_data_corrupt(1, 3)  # corruption is NOT transient
    for _ in range(2):              # N=2 flakes, then the read heals
        with pytest.raises(OSError) as ei:
            spec.maybe_data_io_error(0, 2)
        assert ei.value.errno == errno.EIO
    spec.maybe_data_io_error(0, 2)  # third attempt: clean
    assert spec.maybe_worker_die(0) is False
    assert spec.maybe_worker_die(1) is True
    assert spec.maybe_worker_die(1) is False  # dies ONCE


# ---------------------------------------------------------------------------
# loader-level: retry / worker resurrection / hang / close
# ---------------------------------------------------------------------------

def _loader(roidb, guard=None):
    return AnchorLoader(roidb, driver.tiny_config(), num_shards=1,
                        shuffle=False, seed=0, guard=guard)


def test_loader_transient_retry_stream_bitexact(tmp_path):
    """Two injected EIO flakes on one record: the guarded loader backs
    off, retries, and yields the EXACT stream of an unguarded run."""
    roidb = _roidb()
    baseline = _batches(_loader(roidb))
    guard = FeedGuard(_dcfg(record_backoff_base_s=0.001,
                            record_backoff_max_s=0.002),
                      n_records=len(roidb),
                      chaos_spec=chaos.parse("data_io_error_at=0:2:2"))
    chaosed = _batches(_loader(roidb, guard=guard))
    _assert_streams_equal(baseline, chaosed)
    assert guard.retry_count == 2 and guard.quarantined_count == 0


def test_loader_worker_death_resurrected_stream_intact(tmp_path):
    """A chaos-killed prefetch worker (abrupt return, claim + slot kept)
    is detected by consumer-side supervision, its position requeued, a
    replacement spawned — every batch still arrives, in order."""
    roidb = _roidb()
    baseline = _batches(_loader(roidb))
    elog = EventLog(str(tmp_path / "events_p0.jsonl"))
    guard = FeedGuard(_dcfg(), n_records=len(roidb), elog=elog,
                      chaos_spec=chaos.parse("data_worker_die_at=0"))
    chaosed = _batches(_loader(roidb, guard=guard))
    _assert_streams_equal(baseline, chaosed)
    elog.close()
    deaths = [e for e in report.load_events(str(tmp_path))
              if e["type"] == "data_worker"]
    assert len(deaths) == 1
    assert deaths[0]["resurrected"] is True
    assert deaths[0]["deaths"] == 1 and deaths[0]["restart_max"] == 3


def test_loader_worker_death_budget_exhausted_raises():
    """data.worker_restart_max=0: the first death is over budget —
    DataWorkerError (NOT RuntimeError: graftheal must not retry a
    broken input plane)."""
    roidb = _roidb()
    guard = FeedGuard(_dcfg(worker_restart_max=0), n_records=len(roidb),
                      chaos_spec=chaos.parse("data_worker_die_at=0"))
    loader = _loader(roidb, guard=guard)
    with pytest.raises(DataWorkerError) as ei:
        _batches(loader)
    assert not isinstance(ei.value, RuntimeError)
    loader.close()  # already closed by the raise path: must not hang


def test_loader_hang_raises_datastall_within_deadline():
    """A wedged record read (chaos hang >> deadline) turns into
    DataStallError once the blocking next() outlasts
    data.wait_deadline_s — and close() returns promptly because the
    cancel predicate releases the hung worker."""
    roidb = _roidb()
    guard = FeedGuard(_dcfg(wait_deadline_s=1.0), n_records=len(roidb),
                      chaos_spec=chaos.parse("data_hang_at=0:0 hang_s=60"))
    loader = _loader(roidb, guard=guard)
    t0 = time.monotonic()
    with pytest.raises(DataStallError, match="wait_deadline_s"):
        _batches(loader)
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, elapsed  # deadline + join slack, nowhere near 60
    loader.close()


def test_loader_close_idempotent_and_dead_worker_safe():
    """close() twice is a no-op; closing mid-iteration with a chaos-dead
    worker in the pool (its thread object still in the join list)
    neither hangs nor raises."""
    roidb = _roidb()
    loader = _loader(roidb)
    it = iter(loader)
    next(it)
    loader.close()
    loader.close()
    guard = FeedGuard(_dcfg(), n_records=len(roidb),
                      chaos_spec=chaos.parse("data_worker_die_at=0"))
    loader = _loader(roidb, guard=guard)
    it = iter(loader)
    next(it)        # worker 0 may already be dead; batches still flow
    loader.close()  # join skips dead threads
    loader.close()


# ---------------------------------------------------------------------------
# fit-level: quarantine-complete + kill->resume parity, hang, cap
# (riding tests/_resilience_driver.py — same tiny 64^2 fit as the
# graftguard/graftheal gates)
# ---------------------------------------------------------------------------

RESUMABLE_RC = 75
#: 1 of 3 synthetic records quarantined = 33% — the tiny-fit gates must
#: lift the (production-sized) 1% default to let the run proceed.
_CAP_OVER = {"data.quarantine_max_fraction": 0.5}


def _quarantine_parity(tmp_path, monkeypatch, flat):
    """The tentpole gate: chaos-corrupt record 1 in epoch 0 ->
    quarantined (event + jsonl), run COMPLETES on the deterministic
    substitute; SIGTERM mid-epoch-1 + --resume auto re-applies the
    quarantine file and finishes BIT-exact vs the uninterrupted chaos
    run."""
    monkeypatch.setenv(chaos.ENV_VAR, "data_corrupt_at=0:1")
    chaos.reset()
    obs_u = str(tmp_path / "obs_uninterrupted")
    params_u = driver.run_fit(str(tmp_path / "uninterrupted"), flat=flat,
                              obs_dir=obs_u, over_extra=_CAP_OVER)
    quars = [e for e in report.load_events(obs_u)
             if e["type"] == "data" and e["kind"] == "quarantine"]
    assert len(quars) == 1
    assert quars[0]["record"] == 1 and quars[0]["epoch"] == 0
    assert "corrupt JPEG" in quars[0]["reason"]
    qfile = [json.loads(l)
             for l in open(os.path.join(obs_u, "quarantine.jsonl"))]
    assert len(qfile) == 1 and qfile[0]["record"] == 1
    assert qfile[0]["replacement"] == quars[0]["replacement"]
    # the report folds it (the smoke script greps this line)
    summary = report.summarize(report.load_events(obs_u))
    assert summary["data"]["quarantined"][0]["record"] == 1
    assert "1 record(s) quarantined" in report.render(summary)
    assert report.bench_blob(summary)["data_quarantined"] == 1

    monkeypatch.setenv(chaos.ENV_VAR,
                       "data_corrupt_at=0:1 sigterm_at_step=4")
    chaos.reset()
    obs_k = str(tmp_path / "obs_killed")
    with pytest.raises(PreemptionExit) as ei:
        driver.run_fit(str(tmp_path / "killed"), flat=flat, obs_dir=obs_k,
                       over_extra=_CAP_OVER)
    assert ei.value.code == RESUMABLE_RC

    monkeypatch.setenv(chaos.ENV_VAR, "data_corrupt_at=0:1")
    chaos.reset()
    # SAME obs dir: --resume auto re-applies obs_k/quarantine.jsonl, so
    # the resumed epoch-1 stream substitutes record 1 exactly like the
    # uninterrupted run (which quarantined it back in epoch 0).
    params_r = driver.run_fit(str(tmp_path / "killed"), flat=flat,
                              resume="auto", obs_dir=obs_k,
                              over_extra=_CAP_OVER)
    applied = [e for e in report.load_events(obs_k)
               if e["type"] == "data"
               and e["kind"] == "quarantine_applied"]
    assert len(applied) == 1 and applied[0]["count"] == 1
    import jax

    la = jax.tree_util.tree_leaves_with_path(params_u)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(params_r)}
    assert len(la) == len(lb)
    for path, va in la:
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(lb[jax.tree_util.keystr(path)]),
            err_msg=jax.tree_util.keystr(path))


# The four fit gates below are slow-marked like the graftquorum
# subprocess gates: ~200s of tiny fits that would bust the tier-1 wall
# clock. `script/smoke_resilience.sh` (`pytest -m chaos`) runs them.
@pytest.mark.slow
@pytest.mark.compile_heavy
def test_quarantine_kill_resume_parity_tree(tmp_path, monkeypatch):
    _quarantine_parity(tmp_path, monkeypatch, flat=False)


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_quarantine_kill_resume_parity_flat(tmp_path, monkeypatch):
    """Same contract under train.flat_params: the quarantine set rides
    the run (not the loader instance), so the flat session's rebuilt
    buffers see the identical substituted stream."""
    _quarantine_parity(tmp_path, monkeypatch, flat=True)


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_hang_crashes_with_data_wait_attribution(tmp_path, monkeypatch):
    """Dead storage mid-run: the blocking next() raises DataStallError
    at data.wait_deadline_s (escaping graftheal — not a RuntimeError),
    and the crash flight dump carries a stall event whose phase says
    data_wait, not dispatch."""
    monkeypatch.setenv(chaos.ENV_VAR, "data_hang_at=0:2 hang_s=600")
    chaos.reset()
    obs_dir = str(tmp_path / "obs")
    t0 = time.monotonic()
    with pytest.raises(DataStallError):
        driver.run_fit(str(tmp_path / "hung"), end_epoch=1,
                       obs_dir=obs_dir,
                       over_extra={"data.wait_deadline_s": 4.0,
                                   "obs.stall_min_s": 0.3,
                                   "obs.stall_factor": 0.01,
                                   "obs.watchdog_poll_s": 0.1})
    assert time.monotonic() - t0 < 120.0  # deadline + teardown, not 600
    events = report.load_events(obs_dir)
    stalls = [e for e in events if e["type"] == "stall"]
    assert any(e.get("phase") == "data_wait" for e in stalls), stalls
    crashes = [e for e in events if e["type"] == "crash"]
    assert len(crashes) == 1
    assert "DataStallError" in crashes[0]["error"]
    flight = os.path.join(obs_dir, "flight_crash.json")
    assert os.path.exists(flight)
    ring = json.load(open(flight))["events"]
    assert any(e["type"] == "stall" and e.get("phase") == "data_wait"
               for e in ring)


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_quarantine_cap_aborts_fit(tmp_path, monkeypatch):
    """Under the PRODUCTION default cap (1%), one corrupt record in a
    3-record dataset is a broken dataset: the fit aborts with
    QuarantineExceededError, the evidence persisted and the cap event
    emitted — no silent training on substitutes."""
    monkeypatch.setenv(chaos.ENV_VAR, "data_corrupt_at=0:1")
    chaos.reset()
    obs_dir = str(tmp_path / "obs")
    with pytest.raises(QuarantineExceededError, match="broken"):
        driver.run_fit(str(tmp_path / "capped"), end_epoch=1,
                       obs_dir=obs_dir)
    events = report.load_events(obs_dir)
    kinds = [e["kind"] for e in events if e["type"] == "data"]
    assert "quarantine" in kinds and "quarantine_cap" in kinds
    assert os.path.exists(os.path.join(obs_dir, "quarantine.jsonl"))
    assert os.path.exists(os.path.join(obs_dir, "flight_crash.json"))
    assert report.summarize(events)["data"]["cap_trips"] == 1
