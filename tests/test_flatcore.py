"""flatcore (train/flatcore.py): flat parameter/optimizer-state storage.

Parity gates for the flat update path: the flat-mode train step must match
tree mode (f32 CPU, bit-for-bit for SGD — the update is purely elementwise
— and to reduction-order tolerance for AdamW's global-norm clip), frozen
params must stay bit-identical, TP/PP configs must route back to the
per-leaf path, and checkpoints must interchange between modes bit-for-bit.
The structural kernel-count proof (the ~6 ms many-buffer floor's fix,
PERF.md r6) runs on the CPU backend so it survives TPU outages.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models.faster_rcnn import build_model, forward_train, init_params
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.train import flatcore
from mx_rcnn_tpu.train.optimizer import build_optimizer, trainable_mask
from mx_rcnn_tpu.train.step import create_train_state, make_train_step


def _cfg(**train_over):
    """64^2 f32 micro-config (the test_train_step accum-test shapes)."""
    from dataclasses import replace

    cfg = generate_config(
        "resnet50", "synthetic",
        **{
            "train.rpn_pre_nms_top_n": 128,
            "train.rpn_post_nms_top_n": 32,
            "train.batch_rois": 16,
            "train.max_gt_boxes": 4,
            "train.batch_images": 1,
            "network.anchor_scales": (2, 4),
            "image.pad_shape": (64, 64),
        })
    return cfg.with_updates(
        train=replace(cfg.train, **{"compute_dtype": "f32", **train_over}))


def _batch(b):
    rs = np.random.RandomState(3)
    gt = np.zeros((b, 4, 4), np.float32)
    gt[:, 0] = [8, 8, 40, 40]
    valid = np.zeros((b, 4), bool)
    valid[:, 0] = True
    classes = np.zeros((b, 4), np.int32)
    classes[:, 0] = 1
    return {
        "image": jnp.asarray(rs.randn(b, 64, 64, 3).astype(np.float32)),
        "im_info": jnp.asarray([[64, 64, 1.0]] * b, np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }


def _fake_params(layers=4, frozen=True):
    """Small hand-built tree with frozen (conv0/bn gamma-beta) and
    trainable leaves — update-only tests need no model build."""
    rs = np.random.RandomState(0)
    tree = {"conv0": {"kernel": rs.randn(3, 3, 3, 8).astype(np.float32)}} \
        if frozen else {}
    for i in range(layers):
        tree[f"layer{i:02d}"] = {
            "kernel": rs.randn(8, 8).astype(np.float32),
            "bias": rs.randn(8).astype(np.float32),
        }
    if frozen:
        tree["norm"] = {"gamma": np.ones(8, np.float32),
                        "beta": np.zeros(8, np.float32)}
    tree["bbox_pred"] = {"kernel": rs.randn(8, 8).astype(np.float32),
                         "bias": rs.randn(8).astype(np.float32)}
    return {"params": tree}


def _grads_like(params, scale=1e-2):
    key = jax.random.PRNGKey(7)
    return jax.tree_util.tree_map(
        lambda p: (jax.random.normal(
            jax.random.fold_in(key, p.size), p.shape) * scale
        ).astype(p.dtype), params)


def _leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# segment table
# ---------------------------------------------------------------------------

def test_segment_table_round_trip_and_dtype_segregation():
    params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
              "b": np.arange(4, dtype=np.int32),
              "c": {"d": np.ones((3, 2), np.float32)}}
    mask = {"a": True, "b": False, "c": {"d": True}}
    table = flatcore.SegmentTable(params, mask)
    bufs = table.flatten(params)
    assert set(bufs) == {"float32", "int32"}
    assert bufs["float32"].shape == (12,) and bufs["int32"].shape == (4,)
    _leaves_equal(table.unflatten(bufs), params)
    # offsets follow the canonical flatten spec order ('a' before 'c/d')
    np.testing.assert_array_equal(
        table.segment_view(bufs, "a"), params["a"])
    np.testing.assert_array_equal(
        table.segment_view(bufs, "c/d"), params["c"]["d"])
    masks = table.mask_buffers()
    assert masks["float32"].sum() == 12  # both f32 leaves trainable
    assert masks["int32"].sum() == 0     # 'b' frozen


def test_segment_table_rejects_mismatched_tree():
    params = {"a": np.ones((2, 2), np.float32)}
    table = flatcore.SegmentTable(params, {"a": True})
    with pytest.raises(ValueError, match="leaves"):
        table.flatten({"a": np.ones((2, 2), np.float32),
                       "b": np.ones(3, np.float32)})


# ---------------------------------------------------------------------------
# update-only parity (no forward — fast)
# ---------------------------------------------------------------------------

def test_flat_sgd_update_bit_exact_and_state_round_trip():
    cfg = _cfg()
    params = _fake_params()
    grads = _grads_like(params)
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)

    s_tree = create_train_state(params, tx)
    s_flat = core.init_state(params)
    fgrads = {d: jnp.asarray(b) for d, b in core.table.flatten(grads).items()}
    for _ in range(3):
        s_tree = s_tree.apply_gradients(grads)
        s_flat = s_flat.apply_gradients(fgrads)

    p_flat, o_flat = core.tree_state(s_flat)
    _leaves_equal(s_tree.params, p_flat)       # params bit-for-bit
    _leaves_equal(s_tree.opt_state, o_flat)    # momentum + count bit-for-bit

    # frozen leaves (conv0 kernel, gamma/beta) never moved
    for name in ("conv0", "norm"):
        _leaves_equal(params["params"][name], p_flat["params"][name])

    # tree -> flat -> tree is the identity
    rt_p, rt_o = core.tree_state(core.flatten_state(s_tree))
    _leaves_equal(s_tree.params, rt_p)
    _leaves_equal(s_tree.opt_state, rt_o)


def test_flat_adamw_update_matches_tree():
    """AdamW differs from the tree path only in the global-norm reduction
    order (per-buffer partial sums vs per-leaf) — float-rounding-level."""
    cfg = _cfg(optimizer="adamw", lr=1e-4, clip_gradient=0.1)
    params = _fake_params()
    grads = _grads_like(params)
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)

    s_tree = create_train_state(params, tx)
    s_flat = core.init_state(params)
    fgrads = {d: jnp.asarray(b) for d, b in core.table.flatten(grads).items()}
    for _ in range(3):
        s_tree = s_tree.apply_gradients(grads)
        s_flat = s_flat.apply_gradients(fgrads)

    p_flat, o_flat = core.tree_state(s_flat)
    for a, b in zip(jax.tree_util.tree_leaves(s_tree.params),
                    jax.tree_util.tree_leaves(p_flat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # frozen leaves are EXACT even under adamw (hard-zero update)
    _leaves_equal(params["params"]["conv0"], p_flat["params"]["conv0"])
    # moments/counts line up leaf-for-leaf
    for a, b in zip(jax.tree_util.tree_leaves(s_tree.opt_state),
                    jax.tree_util.tree_leaves(o_flat)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-4, atol=1e-7)


def test_flat_bf16_slot_dtype():
    """opt_state_dtype=bfloat16 flows into the flat trace buffer (the
    memory lever survives the flat layout)."""
    cfg = _cfg(opt_state_dtype="bfloat16")
    params = _fake_params()
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    s_flat = core.init_state(params)
    assert s_flat.slots[0]["float32"].dtype == jnp.bfloat16
    fgrads = {d: jnp.asarray(b)
              for d, b in core.table.flatten(_grads_like(params)).items()}
    s_flat = s_flat.apply_gradients(fgrads)
    # conversion reproduces optax's cast-stored trace bit-for-bit
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    s_tree = create_train_state(params, tx).apply_gradients(
        _grads_like(params))
    _, o_flat = core.tree_state(s_flat)
    _leaves_equal(s_tree.opt_state, o_flat)


# ---------------------------------------------------------------------------
# full-step parity (forward + backward through the flat buffers)
# ---------------------------------------------------------------------------

def test_flat_full_step_bit_exact_sgd():
    """The exactness gate: a full fwd+bwd+update train step in flat mode
    reproduces tree mode bit-for-bit on the f32 CPU backend (like the
    multi-step-dispatch gate), frozen-BN/stem params included."""
    cfg = _cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    batch = _batch(1)
    rng = jax.random.PRNGKey(11)

    tree_step = make_train_step(model, cfg, donate=False)
    s_tree, m_tree = tree_step(create_train_state(params, tx), batch, rng)
    s_tree, _ = tree_step(s_tree, batch, jax.random.PRNGKey(12))

    flat_step = make_train_step(model, cfg, donate=False, flat_core=core)
    s_flat, m_flat = flat_step(core.init_state(params), batch, rng)
    s_flat, _ = flat_step(s_flat, batch, jax.random.PRNGKey(12))

    np.testing.assert_allclose(float(m_tree["TotalLoss"]),
                               float(m_flat["TotalLoss"]), rtol=1e-6)
    p_flat, o_flat = core.tree_state(s_flat)
    _leaves_equal(s_tree.params, p_flat)
    _leaves_equal(s_tree.opt_state, o_flat)
    assert int(s_flat.step) == 2 and int(s_flat.count) == 2

    # frozen-mask coverage on the real model: every frozen leaf identical
    mask = trainable_mask(params, cfg.network.fixed_param_patterns)
    for (path, old), m in zip(jax.tree_util.tree_leaves_with_path(params),
                              jax.tree_util.tree_leaves(mask)):
        if not m:
            new = p_flat
            for entry in path:
                new = new[entry.key]
            np.testing.assert_array_equal(np.asarray(old), np.asarray(new),
                                          err_msg=f"frozen moved: {path}")


def test_flat_multi_step_dispatch_matches_sequential():
    """multi_step_dispatch scans the FLAT state: K=2 stacked batches
    reproduce two sequential flat dispatches bit-for-bit (f32 CPU)."""
    cfg1 = _cfg()
    cfgK = _cfg(multi_step_dispatch=2)
    model = build_model(cfg1)
    params = init_params(model, cfg1, jax.random.PRNGKey(0))
    core = flatcore.FlatCore(cfg1, params, steps_per_epoch=10)
    rng = jax.random.PRNGKey(9)
    b0, b1 = _batch(1), _batch(1)
    b1 = {**b1, "image": b1["image"] + 0.5}

    multi_step = make_train_step(model, cfgK, donate=False, flat_core=core)
    stacked = {k: jnp.stack([b0[k], b1[k]]) for k in b0}
    s_multi, _ = multi_step(core.init_state(params), stacked, rng)

    single = make_train_step(model, cfg1, donate=False, flat_core=core)
    keys = jax.random.split(rng, 2)
    s_seq = core.init_state(params)
    s_seq, _ = single(s_seq, b0, keys[0])
    s_seq, _ = single(s_seq, b1, keys[1])

    assert int(s_multi.step) == 2
    for d in s_multi.flat:
        np.testing.assert_allclose(np.asarray(s_multi.flat[d]),
                                   np.asarray(s_seq.flat[d]),
                                   rtol=1e-5, atol=1e-6)


def test_flat_dp_step_matches_single_device():
    """2-way DP over flat buffers == single device on the same batch: the
    gradient allreduce is ONE psum per buffer and changes nothing."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = _cfg(batch_images=2)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    batch = _batch(2)
    rng = jax.random.PRNGKey(5)

    single = make_train_step(model, cfg, donate=False, flat_core=core)
    s1, m1 = single(core.init_state(params), batch, rng)

    mesh = create_mesh("2")
    dp = make_train_step(model, cfg, mesh=mesh, donate=False,
                         flat_core=core)
    s2, m2 = dp(core.init_state(params), shard_batch(batch, mesh), rng)

    np.testing.assert_allclose(float(m1["TotalLoss"]),
                               float(m2["TotalLoss"]), rtol=1e-4)
    for d in s1.flat:
        np.testing.assert_allclose(np.asarray(s1.flat[d]),
                                   np.asarray(s2.flat[d]),
                                   rtol=2e-3, atol=2e-5)


# ---------------------------------------------------------------------------
# routing: TP/PP keep the per-leaf path
# ---------------------------------------------------------------------------

def test_flat_mode_routing():
    from dataclasses import replace

    from jax.sharding import PartitionSpec as P

    from mx_rcnn_tpu.parallel.partition import flat_segment_specs

    cfg = _cfg(flat_params=True)
    assert flatcore.flat_mode_for(cfg)
    assert not flatcore.flat_mode_for(_cfg())  # knob off
    tp = cfg.with_updates(network=replace(cfg.network, tensor_parallel=True))
    assert not flatcore.flat_mode_for(tp)
    pp = cfg.with_updates(network=replace(cfg.network, pp_stages=2))
    assert not flatcore.flat_mode_for(pp)

    params = _fake_params()
    repl = jax.tree_util.tree_map(lambda _: P(), params)
    specs = flat_segment_specs(params, repl)
    assert specs == {"float32": P()}  # replicated tree -> flat buffers ok
    assert flatcore.flat_mode_for(cfg, params=params, param_specs=repl)

    sharded = jax.tree_util.tree_map(lambda _: P(), params)
    sharded["params"]["layer00"]["kernel"] = P(None, "model")
    assert flat_segment_specs(params, sharded) is None
    assert not flatcore.flat_mode_for(cfg, params=params,
                                      param_specs=sharded)


# ---------------------------------------------------------------------------
# structural proof: kernel-count collapse (CPU backend, outage-proof)
# ---------------------------------------------------------------------------

_ARITH = {"fusion", "multiply", "add", "subtract", "divide", "sqrt",
          "rsqrt", "power", "select", "clamp", "maximum", "minimum",
          "reduce", "negate"}


def _module_arith(text):
    """Arithmetic instructions across the whole compiled module (fusion
    bodies included — on CPU the per-leaf structure lives inside them)."""
    n = 0
    for m in re.finditer(r"=\s*[a-z0-9_\[\],\. ]*?\b([a-z][a-z0-9\-]*)\(",
                         text):
        if m.group(1) in _ARITH:
            n += 1
    return n


def _entry_fusions(text):
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", text, re.S | re.M)
    return sum(1 for line in m.group(1).splitlines() if " fusion(" in line)


@pytest.mark.parametrize("opt", ["sgd", "adamw"])
def test_flat_update_kernel_count_collapses(opt):
    """The compiled flat update is O(1) kernels in the leaf count — ≤ 10
    fused kernels at the program's top level and a few dozen arithmetic
    instructions total — while the per-leaf path scales with the tree
    (hundreds of instructions for a ~100-leaf tree). Same method as the
    packed-RPN 5-conv→1-conv HLO count: structure of the COMPILED program
    on the CPU backend, immune to TPU outages."""
    over = {"optimizer": opt}
    if opt == "adamw":
        over.update(lr=1e-4, clip_gradient=0.1)
    cfg = _cfg(**over)
    params = _fake_params(layers=48)  # ~100 leaves: 'hundreds' per-leaf
    grads = _grads_like(params)
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    fgrads = {d: jnp.asarray(b) for d, b in core.table.flatten(grads).items()}

    tree_fn = jax.jit(lambda s, g: s.apply_gradients(g), donate_argnums=(0,))
    flat_fn = jax.jit(lambda s, g: s.apply_gradients(g), donate_argnums=(0,))
    tree_txt = tree_fn.lower(create_train_state(params, tx),
                             grads).compile().as_text()
    flat_txt = flat_fn.lower(core.init_state(params),
                             fgrads).compile().as_text()

    flat_arith = _module_arith(flat_txt)
    tree_arith = _module_arith(tree_txt)
    assert _entry_fusions(flat_txt) <= 10, flat_txt[:2000]
    assert flat_arith <= 40, f"flat update grew: {flat_arith} arith ops"
    assert tree_arith >= 200, f"per-leaf baseline changed: {tree_arith}"
    assert tree_arith >= 10 * flat_arith, (tree_arith, flat_arith)


# ---------------------------------------------------------------------------
# checkpoint interchange (tree form on disk, both directions, sync + async)
# ---------------------------------------------------------------------------

def test_checkpoint_round_trip_between_modes(tmp_path):
    """A checkpoint saved from a flat-mode run loads into a tree-mode run
    bit-for-bit and vice versa — including the async (orbax) writer. Both
    modes run the identical SGD trajectory, save, and the loaded states
    are compared cross-mode."""
    from mx_rcnn_tpu.train.checkpoint import (
        CheckpointWriter, load_checkpoint, save_checkpoint)

    cfg = _cfg()
    params = _fake_params()
    grads = _grads_like(params)
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    kw = dict(means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
              num_classes=2)  # bbox_pred kernel is 8-wide = 2 classes x 4

    s_tree = create_train_state(params, tx).apply_gradients(grads)
    fgrads = {d: jnp.asarray(b) for d, b in core.table.flatten(grads).items()}
    s_flat = core.init_state(params).apply_gradients(fgrads)

    # flat-mode save goes through tree_state: async writer, tree form
    writer = CheckpointWriter()
    p_save, o_save = core.tree_state(s_flat)
    writer.save(str(tmp_path / "flat"), 1, p_save, o_save, **kw)
    writer.close()
    # tree-mode save: the unchanged sync path
    save_checkpoint(str(tmp_path / "tree"), 1, s_tree.params,
                    s_tree.opt_state, **kw)

    tmpl = {"params": params}
    p_from_flat, o_from_flat = load_checkpoint(
        str(tmp_path / "flat"), 1, template=tmpl,
        opt_state_template=tx.init(params), **kw)
    p_from_tree, o_from_tree = load_checkpoint(
        str(tmp_path / "tree"), 1, template=tmpl,
        opt_state_template=tx.init(params), **kw)

    # on-disk forms are interchangeable: both loads are bit-identical
    _leaves_equal(p_from_flat, p_from_tree)
    _leaves_equal(o_from_flat, o_from_tree)

    # flat-saved checkpoint resumes a TREE run == the live tree state
    # (modulo the bbox_pred fold/unfold both loads share)
    resumed_tree = create_train_state(p_from_flat, tx).replace(
        opt_state=o_from_flat)
    _leaves_equal(resumed_tree.opt_state, s_tree.opt_state)

    # tree-saved checkpoint resumes a FLAT run == the live flat state
    resumed_flat = core.flatten_state(
        create_train_state(p_from_tree, tx).replace(
            opt_state=o_from_tree, step=jnp.asarray(1, jnp.int32)))
    for d in s_flat.slots[0]:
        np.testing.assert_array_equal(
            np.asarray(resumed_flat.slots[0][d]),
            np.asarray(s_flat.slots[0][d]))
    assert int(resumed_flat.count) == int(s_flat.count)


def test_fit_detector_flat_smoke(tmp_path):
    """End-to-end: fit_detector with train.flat_params trains, saves a
    TREE-form checkpoint (loadable with plain load_checkpoint + an optax
    template), and returns a host param tree."""
    from dataclasses import replace

    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector
    from mx_rcnn_tpu.train.checkpoint import load_checkpoint

    cfg = _cfg(flat_params=True, flip=False, lr_step=(100,))
    cfg = cfg.with_updates(image=replace(cfg.image, scales=((64, 64),)))
    ds = SyntheticDataset("train", num_images=3, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    history = []
    final = fit_detector(cfg, ds.gt_roidb(), prefix=str(tmp_path / "flat"),
                         end_epoch=1, frequent=1000, seed=0, mesh_spec="1",
                         epoch_callback=lambda e, s, b: history.append(
                             (int(s.step), b.get()["TotalLoss"])))
    assert len(history) == 1 and history[0][0] == 3, history
    assert np.isfinite(history[0][1])
    # checkpoint is in tree form: restores against a tree template
    model = build_model(cfg)
    tmpl = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, tmpl, steps_per_epoch=3)
    loaded, opt = load_checkpoint(
        str(tmp_path / "flat"), 1, template={"params": tmpl},
        opt_state_template=tx.init(tmpl),
        means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
        num_classes=cfg.dataset.num_classes)
    assert opt is not None
    _leaves_equal(jax.tree_util.tree_map(lambda x: np.asarray(x).shape,
                                         loaded),
                  jax.tree_util.tree_map(lambda x: np.asarray(x).shape,
                                         final))
