"""grafttower (obs/fleet.py) gates — fleet-scope observability.

Two layers, same split as test_quorum.py:

- **fold units** (tier-1): hand-built two-host streams with deliberate
  wall-clock skew pin the merge/alignment contract; heartbeat cadence +
  stale (hung) detection; barrier-event emission and wait attribution;
  the ``--fleet`` CLI fold; torn-line byte-offset warnings.
- **ONE trainer gate** (``slow``): a real 2-sim-host run where chaos
  ``slow_step_at`` drags one host's every dispatch — after an injected
  +300 s wall skew on that host's stream, the fleet report must still
  merge the timelines, rank the injected host straggler, attribute the
  barrier wait to it, and flag it hung once ``host_die_at_step``
  SIGKILLs it (stale heartbeat trail, no final beat).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from mx_rcnn_tpu.obs import open_event_log, report
from mx_rcnn_tpu.obs.fleet import fleet_summary, merge_streams, render_fleet
from mx_rcnn_tpu.obs.watchdog import StallWatchdog
from mx_rcnn_tpu.resilience import FileKVStore, Quorum

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO_ROOT, "tests", "_resilience_driver.py")

#: injected wall-clock offset for the skew fixtures/gate (seconds) —
#: deliberately huge so a fold that trusts t_wall cannot pass by luck.
SKEW = 300.0


# ---------------------------------------------------------------------------
# stream builders (hand-built two-host fixtures)
# ---------------------------------------------------------------------------

def _rec(type_, host, t_true, *, wall_skew=0.0, mono_origin=0.0, **fields):
    """One event as host ``host``'s EventLog would stamp it at true time
    ``t_true``: its wall clock reads true + its skew; its monotonic
    clock has an arbitrary per-process origin."""
    rec = {"type": type_, "t_wall": t_true + wall_skew,
           "t_mono": (t_true - 1000.0) + mono_origin,
           "process": host, "step": fields.pop("step", 0)}
    rec.update(fields)
    return rec


def _h0(type_, t_true, **fields):
    return _rec(type_, 0, t_true, wall_skew=0.0, mono_origin=50.0,
                **fields)


def _h1(type_, t_true, **fields):
    # host 1's wall clock runs SKEW seconds ahead (an NTP step the fleet
    # never noticed); its monotonic origin is unrelated to host 0's.
    return _rec(type_, 1, t_true, wall_skew=SKEW, mono_origin=7000.0,
                **fields)


def _two_host_streams(hung=False):
    """Two synthetic host streams over ~25 s of true time: host 1 runs a
    +0.25 s per-dispatch tail (the straggler) and, when ``hung``, is
    killed at true t=1013 — its heartbeat trail just stops, no final
    beat, while host 0 lives on. The epoch/1 barrier (host 0 waited
    1.0 s for host 1; released within one poll of the same true instant)
    is always present: it is the residual-skew correction signal, and in
    the real run it fires before any kill too."""
    h0 = [_h0("run_meta", 1000.2, batch_size=1)]
    h1 = [_h1("run_meta", 1000.7)]
    for i in range(5):
        t = 1002.0 + 2.0 * i
        h0.append(_h0("step", t, step_ms=400.0, data_wait_ms=5.0,
                      epoch=0, batch=i + 1, step=i + 1))
        h1.append(_h1("step", t + 0.25, step_ms=650.0,
                      data_wait_ms=5.0, epoch=0, batch=i + 1,
                      step=i + 1))
    h0.append(_h0("barrier", 1012.0, name="epoch/1", wait_s=1.0,
                  arrived=[0, 1], absent=[], order=[0, 1], last=1,
                  timed_out=False))
    h1.append(_h1("barrier", 1012.02, name="epoch/1", wait_s=0.02,
                  arrived=[0, 1], absent=[], order=[0, 1], last=1,
                  timed_out=False))
    for t in (1001.0, 1006.0, 1011.0):
        h0.append(_h0("heartbeat", t, every_s=5.0, beat_age_s=0.2,
                      stalls=0, final=False))
    for t in (1001.5, 1006.5, 1011.5):
        h1.append(_h1("heartbeat", t, every_s=5.0, beat_age_s=0.2,
                      stalls=0, final=False))
    if not hung:
        h0.append(_h0("heartbeat", 1013.0, every_s=5.0, beat_age_s=0.2,
                      stalls=0, final=True))
        h1.append(_h1("heartbeat", 1013.1, every_s=5.0, beat_age_s=0.2,
                      stalls=0, final=True))
    else:
        # host 1 died at 1013 (trail above is its last word); host 0
        # lived on alone waiting at the next barrier — the fleet clock
        # keeps ticking past host 1's death, then host 0 shuts down
        # cleanly with its final beat.
        for t in (1016.0, 1021.0, 1026.0):
            h0.append(_h0("heartbeat", t, every_s=5.0, beat_age_s=3.0,
                          stalls=0, final=False))
        h0.append(_h0("heartbeat", 1027.0, every_s=5.0, beat_age_s=3.0,
                      stalls=0, final=True))
    return {0: h0, 1: h1}


# ---------------------------------------------------------------------------
# merge / skew alignment
# ---------------------------------------------------------------------------

def test_merge_aligns_injected_wall_skew():
    """The +300 s wall skew must cancel: barrier releases land within a
    poll interval on the merged timeline, and per-dispatch interleaving
    follows TRUE time (host 1's completion right after host 0's), not
    the skewed wall stamps."""
    merged = merge_streams(_two_host_streams())
    assert [e["t_fleet"] for e in merged] == sorted(
        e["t_fleet"] for e in merged)
    bars = {e["process"]: e["t_fleet"] for e in merged
            if e["type"] == "barrier"}
    assert abs(bars[0] - bars[1]) < 0.5, bars  # raw skew was 300 s
    # recovered per-host clock offsets ride on the reference run_meta
    meta = next(e for e in merged if "fleet_offsets" in e)
    assert 299.0 < float(meta["fleet_offsets"]["1"]) < 301.0
    # dispatch k: h0 completes, then h1 0.25 s later, BEFORE h0's k+1
    steps = [(e["process"], e["batch"]) for e in merged
             if e["type"] == "step"]
    for i in range(1, 6):
        assert steps.index((1, i)) == steps.index((0, i)) + 1


def test_merge_without_barriers_stands_on_anchors():
    """No shared barriers → no residual correction, but the anchor
    projection alone must already order unskewed streams correctly."""
    streams = _two_host_streams()
    for s in streams.values():
        s[:] = [e for e in s if e["type"] != "barrier"]
        for e in s:
            if e["process"] == 1:
                e["t_wall"] -= SKEW  # honest clocks this time
    merged = merge_streams(streams)
    steps = [(e["process"], e["batch"]) for e in merged
             if e["type"] == "step"]
    for i in range(1, 6):
        assert steps.index((1, i)) == steps.index((0, i)) + 1


# ---------------------------------------------------------------------------
# the fold: straggler ranking, barrier attribution, hung detection
# ---------------------------------------------------------------------------

def test_fleet_summary_ranks_straggler_and_attributes_barrier_wait():
    fs = fleet_summary(_two_host_streams())
    assert fs["straggler"] == 1
    assert fs["straggler_ranking"][0] == 1
    assert (fs["per_host"][1]["lateness_s"]
            > fs["per_host"][0]["lateness_s"])
    # every shared dispatch was 0.25 s apart
    assert 0.2 < fs["skew"]["p50_s"] < 0.3
    # host 0's 1.0 s of barrier wait is OWED by host 1 (it arrived last)
    assert fs["barriers"]["rounds"] == 1
    assert fs["barriers"]["owed_s"][1] == pytest.approx(1.0)
    assert fs["per_host"][1]["barrier_wait_owed_s"] == pytest.approx(1.0)
    assert fs["per_host"][0]["barrier_wait_owed_s"] == 0.0
    assert fs["hung"] == []
    assert fs["per_host"][0]["heartbeat"]["status"] == "clean"
    out = render_fleet(fs)
    assert "straggler table" in out and "straggler:  host 1" in out


def test_fleet_summary_flags_killed_host_as_hung():
    """A SIGKILLed host's trail: fresh-until-death heartbeats, no final
    beat, stream ends while the fleet clock keeps running — that is
    ``hung``, and distinct from host 0's clean final beat."""
    fs = fleet_summary(_two_host_streams(hung=True))
    assert fs["hung"] == [1]
    hb1 = fs["per_host"][1]["heartbeat"]
    assert hb1["status"] == "hung" and not hb1["final"]
    assert hb1["age_s"] > 2.0 * hb1["every_s"]
    assert fs["per_host"][0]["heartbeat"]["status"] == "clean"
    assert "HUNG" in render_fleet(fs)


def test_fleet_summary_without_heartbeats_says_so():
    streams = _two_host_streams()
    for s in streams.values():
        s[:] = [e for e in s if e["type"] != "heartbeat"]
    fs = fleet_summary(streams)
    assert fs["per_host"][0]["heartbeat"]["status"] == "no-heartbeats"
    assert fs["hung"] == []


# ---------------------------------------------------------------------------
# heartbeat emission (obs/watchdog.py)
# ---------------------------------------------------------------------------

def _events(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


def test_heartbeat_cadence_and_final_beat(tmp_path):
    """Synchronously driven cadence: first call beats, within-interval
    calls don't, the next interval does; stop() appends exactly one
    final beat (the clean-shutdown marker a SIGKILL can never leave)."""
    log = open_event_log(str(tmp_path), process_index=0)
    wd = StallWatchdog(log, poll_s=60.0, heartbeat_every_s=5.0)
    assert wd.maybe_heartbeat(now=100.0)
    assert not wd.maybe_heartbeat(now=102.0)   # inside the interval
    assert wd.maybe_heartbeat(now=105.5)
    wd.stop()  # thread never started; still emits the final beat
    log.close()
    beats = [e for e in _events(log.path) if e["type"] == "heartbeat"]
    assert len(beats) == 3
    assert [b["final"] for b in beats] == [False, False, True]
    assert all(b["every_s"] == 5.0 and "beat_age_s" in b for b in beats)


def test_heartbeat_rides_watchdog_thread(tmp_path):
    """Thread mode: the beacon shares the watchdog daemon thread and
    beats at its own (shorter) cadence."""
    log = open_event_log(str(tmp_path), process_index=0)
    wd = StallWatchdog(log, poll_s=60.0, heartbeat_every_s=0.02)
    wd.start()
    time.sleep(0.2)
    wd.stop()
    log.close()
    beats = [e for e in _events(log.path) if e["type"] == "heartbeat"]
    assert len(beats) >= 3  # ~10 intervals elapsed; be scheduler-lenient
    assert sum(b["final"] for b in beats) == 1
    assert beats[-1]["final"]


def test_heartbeat_disabled_by_default_knob(tmp_path):
    log = open_event_log(str(tmp_path), process_index=0)
    wd = StallWatchdog(log, poll_s=60.0)  # heartbeat_every_s=0
    assert not wd.maybe_heartbeat(now=100.0)
    wd.stop()
    log.close()
    assert [e for e in _events(log.path)
            if e["type"] == "heartbeat"] == []


# ---------------------------------------------------------------------------
# barrier events (resilience/quorum.py)
# ---------------------------------------------------------------------------

def test_barrier_emits_typed_event_with_order_and_last(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    log0 = open_event_log(str(tmp_path / "obs"), process_index=0)
    log1 = open_event_log(str(tmp_path / "obs"), process_index=1)
    q0 = Quorum(store, 0, 2, timeout_s=5.0, poll_s=0.005, elog=log0)
    q1 = Quorum(store, 1, 2, timeout_s=5.0, poll_s=0.005, elog=log1)
    t = threading.Thread(target=q0.barrier, args=("epoch/1",))
    t.start()
    time.sleep(0.08)  # host 0 sits in the barrier; host 1 arrives last
    q1.barrier("epoch/1")
    t.join(timeout=5.0)
    log0.close()
    log1.close()
    (b0,) = [e for e in _events(log0.path) if e["type"] == "barrier"]
    (b1,) = [e for e in _events(log1.path) if e["type"] == "barrier"]
    for b in (b0, b1):
        assert b["name"] == "epoch/1"
        assert b["arrived"] == [0, 1] and b["absent"] == []
        assert b["order"] == [0, 1] and b["last"] == 1
        assert not b["timed_out"]
    assert b0["wait_s"] > 0.05       # host 0 paid host 1's lateness
    assert b1["wait_s"] < b0["wait_s"]


def test_barrier_timeout_event_marks_absentee(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    log0 = open_event_log(str(tmp_path / "obs"), process_index=0)
    q0 = Quorum(store, 0, 2, timeout_s=0.1, poll_s=0.005, elog=log0)
    arrived = q0.barrier("save/1")
    assert arrived == {0}
    log0.close()
    (b,) = [e for e in _events(log0.path) if e["type"] == "barrier"]
    assert b["timed_out"] and b["absent"] == [1] and b["last"] == 0


def test_barrier_tolerates_legacy_stampless_arrivals(tmp_path):
    """A pre-grafttower writer published "1", not a wall stamp: the
    event still emits — that host just drops out of the order."""
    store = FileKVStore(str(tmp_path / "kv"))
    store.set("epoch/1/arrive/0", "1")  # legacy arrival value
    log1 = open_event_log(str(tmp_path / "obs"), process_index=1)
    q1 = Quorum(store, 1, 2, timeout_s=5.0, poll_s=0.005, elog=log1)
    q1.barrier("epoch/1")
    log1.close()
    (b,) = [e for e in _events(log1.path) if e["type"] == "barrier"]
    assert b["arrived"] == [0, 1]
    assert b["order"] == [1] and b["last"] == 1


# ---------------------------------------------------------------------------
# stream discovery + torn-line warnings (obs/report.py satellites)
# ---------------------------------------------------------------------------

def test_load_events_folds_all_per_host_streams(tmp_path):
    d = str(tmp_path / "obs")
    for idx in (0, 1, 2):
        log = open_event_log(d, process_index=idx)
        log.emit("heal", downtime_s=float(idx))
        log.close()
    events = report.load_events(d)
    assert {e["process"] for e in events} == {0, 1, 2}
    assert report.summarize(events)["heals"]["count"] == 3


def test_load_events_still_reads_legacy_stream_names(tmp_path):
    d = tmp_path / "obs"
    d.mkdir()
    for name, host in (("events.jsonl", 0), ("events.1.jsonl", 1)):
        (d / name).write_text(json.dumps(
            {"type": "heal", "t_wall": 1.0, "t_mono": 1.0,
             "process": host, "step": 0}) + "\n")
    events = report.load_events(str(d))
    assert {e["process"] for e in events} == {0, 1}


def test_torn_line_warning_names_file_and_byte_offset(tmp_path, capsys):
    path = tmp_path / "events_p1.jsonl"
    good = json.dumps({"type": "heal", "t_wall": 1.0, "t_mono": 1.0,
                       "process": 1, "step": 0}) + "\n"
    path.write_text(good + '{"type": "step", "t_wall": 2.')  # torn tail
    records = report.load_jsonl_tolerant(str(path))
    assert len(records) == 1
    err = capsys.readouterr().err
    assert str(path) in err
    assert f"byte {len(good.encode())}" in err


# ---------------------------------------------------------------------------
# the --fleet CLI fold
# ---------------------------------------------------------------------------

def _write_streams(d, streams):
    os.makedirs(d, exist_ok=True)
    for idx, recs in streams.items():
        with open(os.path.join(d, f"events_p{idx}.jsonl"), "w",
                  encoding="utf-8") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")


def test_report_fleet_cli_prints_straggler_table(tmp_path, capsys):
    d = str(tmp_path / "obs")
    _write_streams(d, _two_host_streams())
    blob_path = str(tmp_path / "fleet.json")
    rc = report.main(["--fleet", d, "--json", blob_path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "grafttower fleet report" in out
    assert "straggler table" in out and "straggler:  host 1" in out
    with open(blob_path, encoding="utf-8") as fh:
        blob = json.load(fh)
    assert blob["fleet_straggler"] == 1
    assert blob["fleet_barrier_wait_s"] == pytest.approx(1.02)
    assert 0.2 < blob["fleet_skew_p50_s"] < 0.3
    assert blob["detail"]["fleet"]["barriers"]["rounds"] == 1


def test_report_fleet_cli_rejects_non_directory(tmp_path, capsys):
    path = tmp_path / "events_p0.jsonl"
    path.write_text("")
    assert report.main(["--fleet", str(path)]) == 2


# ---------------------------------------------------------------------------
# the 2-sim-host trainer gate
# ---------------------------------------------------------------------------

def _spawn_fleet_host(idx, n_hosts, prefix, kv_dir, obs_dir, chaos_env,
                      timeout_s):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               MX_RCNN_CHAOS=chaos_env)
    for k in ("MXRCNN_SIM_PROCESS_ID", "MXRCNN_SIM_NUM_PROCESSES"):
        env.pop(k, None)
    cmd = [sys.executable, DRIVER, "--fit", prefix,
           "--sim-host", str(idx), "--sim-hosts", str(n_hosts),
           "--quorum-dir", kv_dir, "--quorum-timeout", str(timeout_s),
           "--obs-dir", obs_dir,
           "--set", "obs.heartbeat_every_s=0.2"]
    return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _skew_stream(path, offset_s):
    """Simulate the NTP skew a real fleet would have: shift every wall
    stamp of one host's (possibly torn — it was SIGKILLed) stream."""
    records = report.load_jsonl_tolerant(path)
    with open(path, "w", encoding="utf-8") as fh:
        for r in records:
            r["t_wall"] = float(r.get("t_wall", 0.0)) + offset_s
            fh.write(json.dumps(r) + "\n")


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.compile_heavy
def test_fleet_report_attributes_chaos_slowed_then_killed_host(tmp_path):
    """The ISSUE acceptance run: host 1 drags a chaos-injected 200 ms
    tail on every dispatch of epoch 1 (straggler), then host_die_at_step
    SIGKILLs it at the first dispatch of epoch 2 (hung). Host 0 rides
    the epoch/2 barrier to its deadline and completes alone. After a
    +300 s wall-skew injection on host 1's stream, the fleet fold must
    still (a) merge the timelines, (b) rank host 1 straggler and hand it
    the barrier wait, (c) flag host 1 hung via its stale heartbeat
    trail."""
    prefix = str(tmp_path / "run")
    kv = str(tmp_path / "kv")
    obs = str(tmp_path / "obs")
    chaos_env = "slow_step_at=1:1:200 host_die_at_step=1:4"
    procs = [_spawn_fleet_host(i, 2, prefix, kv, obs, chaos_env,
                               timeout_s=15)
             for i in range(2)]
    outs = [p.communicate(timeout=570)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0][-2000:]
    assert procs[1].returncode == -9, outs[1][-2000:]  # SIGKILLed

    _skew_stream(os.path.join(obs, "events_p1.jsonl"), SKEW)
    hosts = {idx: report.load_jsonl_tolerant(path)
             for idx, path in report.event_streams(obs).items()}
    assert set(hosts) == {0, 1}
    fs = fleet_summary(hosts)

    # (a) merged despite the injected skew: the recovered offset is the
    # injection (to within barrier-release jitter)
    assert 298.0 < float(fs["offsets_s"]["1"]) < 302.0
    # (b) straggler + barrier-wait attribution
    assert fs["straggler"] == 1
    assert (fs["per_host"][1]["lateness_s"]
            > fs["per_host"][0]["lateness_s"])
    assert (fs["barriers"]["owed_s"][1]
            > fs["barriers"]["owed_s"].get(0, 0.0))
    # (c) hung, not slow-and-alive: beats stopped, no final beat, while
    # host 0 closed its stream with one
    assert fs["hung"] == [1]
    assert fs["per_host"][0]["heartbeat"]["status"] == "clean"

    # the CLI smoke the runbook (and script/smoke_resilience.sh) uses
    proc = subprocess.run(
        [sys.executable, "-m", "mx_rcnn_tpu.obs.report", "--fleet", obs],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "straggler table" in proc.stdout
    assert "HUNG" in proc.stdout
