"""FPN detector: neck, anchors, level assignment, pyramid pooling, forwards.

Covers BASELINE.json configs 3-4 machinery (models/fpn.py,
targets/mask_targets.py). The reference repo has no FPN; semantics follow
Lin et al. (FPN) / He et al. (Mask R-CNN) as documented in the module.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import fpn as F
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.ops.roi_align import roi_align
from mx_rcnn_tpu.targets.mask_targets import mask_targets_for_rois


def tiny_cfg(mask=False, **overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 1,
        "train.fpn_rpn_pre_nms_per_level": 64,
        "train.rpn_post_nms_top_n": 64,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
        "train.mask_gt_resolution": 28,
        "test.fpn_rpn_pre_nms_per_level": 32,
        "test.rpn_post_nms_top_n": 16,
    }
    base.update(overrides)
    net = "resnet50_fpn_mask" if mask else "resnet50_fpn"
    return generate_config(net, "synthetic", **base)


def tiny_batch(rng, mask=False):
    gm = np.zeros((1, 8, 28, 28), np.uint8)
    gm[0, :2, 6:22, 6:22] = 1
    batch = {
        "image": rng.randn(1, 128, 128, 3).astype(np.float32),
        "im_info": np.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": np.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": np.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": np.asarray([[True, True] + [False] * 6]),
    }
    if mask:
        batch["gt_masks"] = gm
    return batch


def test_upsample2x():
    x = jnp.arange(4, dtype=jnp.float32).reshape(1, 2, 2, 1)
    y = F._upsample2x(x)
    assert y.shape == (1, 4, 4, 1)
    assert np.array_equal(np.asarray(y)[0, :, :, 0],
                          [[0, 0, 1, 1], [0, 0, 1, 1],
                           [2, 2, 3, 3], [2, 2, 3, 3]])


def test_neck_shapes():
    neck = F.FPNNeck(channels=32, dtype=jnp.float32)
    feats = [jnp.zeros((1, 32, 32, 8)), jnp.zeros((1, 16, 16, 16)),
             jnp.zeros((1, 8, 8, 32)), jnp.zeros((1, 4, 4, 64))]
    params = neck.init(jax.random.PRNGKey(0), feats)
    out = neck.apply(params, feats)
    assert set(out.keys()) == {2, 3, 4, 5, 6}
    assert out[2].shape == (1, 32, 32, 32)
    assert out[5].shape == (1, 4, 4, 32)
    assert out[6].shape == (1, 2, 2, 32)


def test_pyramid_anchor_sizes():
    cfg = tiny_cfg()
    shapes = {2: (32, 32), 3: (16, 16), 4: (8, 8), 5: (4, 4), 6: (2, 2)}
    anchors = F.pyramid_anchors(shapes, cfg)
    for lv in F.RPN_LEVELS:
        a = anchors[lv]
        assert a.shape == (shapes[lv][0] * shapes[lv][1] * 3, 4)
        # The 1:1-ratio anchor at each level is (scale*stride) square:
        # 8 * 2^lv px. Ratio enumeration rounds, so allow 1px.
        w = a[:, 2] - a[:, 0] + 1
        h = a[:, 3] - a[:, 1] + 1
        square = np.abs(w - h) < 1e-3
        assert square.any()
        np.testing.assert_allclose(w[square][0], 8 * 2 ** lv, atol=1.0)


def test_roi_levels_eq1():
    rois = jnp.asarray([
        [0, 0, 223, 223],    # 224x224 -> k0 = 4
        [0, 0, 111, 111],    # 112 -> 3
        [0, 0, 447, 447],    # 448 -> 5
        [0, 0, 20, 20],      # tiny -> clamp 2
        [0, 0, 2000, 2000],  # huge -> clamp 5
    ], jnp.float32)
    np.testing.assert_array_equal(np.asarray(F.roi_levels(rois)),
                                  [4, 3, 5, 2, 5])


def test_pyramid_roi_align_selects_assigned_level(rng):
    cfg = tiny_cfg()
    pyramid = {lv: jnp.asarray(
        rng.randn(1, 128 // 2 ** lv, 128 // 2 ** lv, 8).astype(np.float32))
        for lv in (2, 3, 4, 5)}
    # One roi per level: sizes 56 (k=2), 112 (k=3), 224->but image is 128...
    # use sizes mapping to levels 2 and 3 inside the image.
    rois = jnp.asarray([[[4, 4, 59, 59], [4, 4, 115, 115]]], jnp.float32)
    valid = jnp.ones((1, 2), bool)
    out = F.pyramid_roi_align(pyramid, rois, valid, pool_size=7)
    assert out.shape == (2, 7, 7, 8)
    lv_of = np.asarray(F.roi_levels(rois[0]))
    for i, lv in enumerate(lv_of):
        flat = jnp.asarray([[0, *np.asarray(rois)[0, i]]], jnp.float32)
        want = roi_align(pyramid[int(lv)], flat, 7, 1.0 / 2 ** int(lv))
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(want[0]),
                                   rtol=1e-5, atol=1e-5)


def test_per_level_nms_union_suppression():
    """Direct check of the per-level scope on constructed candidates:
    same-level near-duplicates ARE suppressed, cross-level near-duplicates
    are NOT (Detectron semantics), and the union is score-ranked."""
    # level A: two heavy-overlap boxes (IoU ~0.9) + one separate
    la = jnp.asarray([[[0, 0, 100, 100], [2, 2, 102, 102],
                       [200, 200, 300, 300]]], jnp.float32)
    sa = jnp.asarray([[0.9, 0.8, 0.6]], jnp.float32)
    # level B: a near-duplicate of level A's best box
    lb = jnp.asarray([[[1, 1, 101, 101], [400, 0, 500, 80],
                       [0, 0, 0, 0]]], jnp.float32)
    sb = jnp.asarray([[0.7, 0.5, 0.0]], jnp.float32)
    valid = jnp.asarray([[True, True, True]])
    vb = jnp.asarray([[True, True, False]])

    rois, kv, scores = F.per_level_nms_union(
        [la, lb], [sa, sb], [valid, vb], thresh=0.5, post=6)
    rois, kv, scores = map(np.asarray, (rois, kv, scores))
    got = {tuple(r) for r in rois[0][kv[0]]}
    # within level A, (2,2,102,102) suppressed by (0,0,100,100)
    assert (2, 2, 102, 102) not in got
    # across levels, the near-duplicate from level B survives
    assert (1, 1, 101, 101) in got
    assert (0, 0, 100, 100) in got and (200, 200, 300, 300) in got
    assert (400, 0, 500, 80) in got
    assert kv[0].sum() == 4
    s = scores[0][kv[0]]
    assert (np.diff(s) <= 1e-6).all()  # union score-ranked
    np.testing.assert_allclose(sorted(s, reverse=True),
                               [0.9, 0.7, 0.6, 0.5], rtol=1e-6)


def test_per_level_nms_semantics(rng):
    """fpn_nms_per_level (Detectron-lineage default): within one level no
    two kept rois overlap past the threshold, the union is score-ranked,
    and the joint variant (False) still runs and returns valid rois."""
    from functools import partial

    from mx_rcnn_tpu.ops.boxes import bbox_overlaps

    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    images = jnp.asarray(batch["image"])
    info = jnp.asarray(batch["im_info"])

    def props(p, x, i, per_level):
        c = cfg.with_updates(train=__import__("dataclasses").replace(
            cfg.train, fpn_nms_per_level=per_level))
        _, rpn_out, anchors = F._pyramid_rpn(model, p, x, c)
        return F.fpn_proposals(rpn_out, anchors, i, c, train=True)

    rois_pl, valid_pl, scores_pl = jax.jit(
        partial(props, per_level=True))(params, images, info)
    rois_j, valid_j, scores_j = jax.jit(
        partial(props, per_level=False))(params, images, info)

    for rois, valid, scores in ((rois_pl, valid_pl, scores_pl),
                                (rois_j, valid_j, scores_j)):
        rois, valid, scores = map(np.asarray, (rois, valid, scores))
        assert valid.any()
        v = rois[0][valid[0]]
        assert np.isfinite(v).all()
        assert (v[:, 2] >= v[:, 0]).all() and (v[:, 3] >= v[:, 1]).all()
        # scores of valid rois are sorted descending (top-k output order)
        s = scores[0][valid[0]]
        assert (np.diff(s) <= 1e-6).all()

    # joint NMS guarantees global non-overlap; per-level only guarantees
    # it within a level — so the joint survivors must pairwise clear the
    # threshold, which pins the two variants really do differ in scope.
    vj = np.asarray(rois_j)[0][np.asarray(valid_j)[0]]
    iou = np.array(bbox_overlaps(vj, vj))  # copy: jax view is read-only
    np.fill_diagonal(iou, 0.0)
    assert iou.max() <= cfg.train.rpn_nms_thresh + 1e-5


def test_forward_train_finite_and_jit(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    for k in ("rpn_cls_loss", "rpn_bbox_loss", "rcnn_cls_loss",
              "rcnn_bbox_loss"):
        assert np.isfinite(float(aux[k])), k


def test_forward_train_grads_reach_all_parts(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    grads = jax.jit(jax.grad(
        lambda p: zoo.forward_train(model, p, batch,
                                    jax.random.PRNGKey(1), cfg)[0]
    ))(params)
    flat = jax.tree_util.tree_flatten_with_path(grads)[0]

    def norm_of(substr):
        tot = 0.0
        for path, leaf in flat:
            if substr in jax.tree_util.keystr(path):
                tot += float(jnp.sum(jnp.abs(leaf)))
        return tot

    for part in ("neck", "rpn", "head", "cls_score", "bbox_pred", "stage3"):
        assert norm_of(part) > 0, f"no gradient reached {part}"
    # Frozen prefix: stage1 gradient is structurally zero.
    assert norm_of("stage1") == 0


def test_forward_test_contract(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    rois, rv, scores, boxes = jax.jit(
        lambda p, i, ii: zoo.forward_test(model, p, i, ii, cfg)
    )(params, batch["image"], batch["im_info"])
    r = cfg.test.rpn_post_nms_top_n
    c = cfg.dataset.num_classes
    assert rois.shape == (1, r, 4)
    assert scores.shape == (1, r, c)
    assert boxes.shape == (1, r, 4 * c)
    # Scores on invalid rois are zeroed.
    s = np.asarray(scores)
    v = np.asarray(rv)
    assert (s[~v] == 0).all()


def test_mask_targets_identity_roi():
    # ROI == gt box: the target must reproduce the gt mask at 28x28.
    gt_boxes = jnp.asarray([[10.0, 20.0, 65.0, 75.0]])
    gm = np.zeros((1, 28, 28), np.float32)
    gm[0, 7:21, 7:21] = 1
    t = mask_targets_for_rois(
        jnp.asarray([[10.0, 20.0, 65.0, 75.0]]), jnp.asarray([0]),
        gt_boxes, jnp.asarray(gm), resolution=28)
    np.testing.assert_array_equal(np.asarray(t)[0], gm[0])


def test_mask_targets_half_roi():
    # ROI = left half of the gt box: target is the left half of the mask,
    # stretched to full resolution.
    gt_boxes = jnp.asarray([[0.0, 0.0, 55.0, 55.0]])
    gm = np.zeros((1, 28, 28), np.float32)
    gm[0, :, :14] = 1  # left half on
    t = mask_targets_for_rois(
        jnp.asarray([[0.0, 0.0, 27.0, 55.0]]), jnp.asarray([0]),
        gt_boxes, jnp.asarray(gm), resolution=28)
    got = np.asarray(t)[0]
    # Almost all columns should be on (right boundary cell may waver).
    assert got[:, :26].all()


def test_mask_targets_outside_gt_box_is_zero():
    gt_boxes = jnp.asarray([[0.0, 0.0, 27.0, 27.0]])
    gm = np.ones((1, 28, 28), np.float32)
    t = mask_targets_for_rois(
        jnp.asarray([[100.0, 100.0, 127.0, 127.0]]), jnp.asarray([0]),
        gt_boxes, jnp.asarray(gm), resolution=28)
    assert np.asarray(t).sum() == 0


def test_mask_forward_train(rng):
    cfg = tiny_cfg(mask=True)
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng, mask=True)
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert np.isfinite(float(aux["mask_loss"]))
    assert float(aux["mask_loss"]) > 0


def test_mask_inference_contract(rng):
    cfg = tiny_cfg(mask=True)
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng, mask=True)
    det_boxes = jnp.asarray([[[10, 10, 60, 90], [70, 20, 120, 70]]],
                            jnp.float32)
    det_classes = jnp.asarray([[1, 2]], jnp.int32)
    det_valid = jnp.asarray([[True, False]])
    probs = jax.jit(lambda p: F.forward_test_masks(
        model, p, batch["image"], det_boxes, det_classes, det_valid))(params)
    assert probs.shape == (1, 2, 28, 28)
    p = np.asarray(probs)
    assert (p[0, 1] == 0).all()  # invalid detection zeroed
    assert ((p >= 0) & (p <= 1)).all()


@pytest.mark.xfail(
    not hasattr(jax.lax, "pvary") and not hasattr(jax.lax, "pcast"),
    reason="pre-varying-type jax (< 0.5): the old partitioner's bf16 "
           "reduction order drifts the DP loss ~0.6% past the rtol "
           "calibrated on newer XLA (see test_pipeline.py's marker)",
    strict=False)
def test_fpn_dp_parity(rng):
    """FPN train step: 2-way DP == single device on the same 2-image batch
    (the pattern of tests/test_train_step.py::test_dp_grads_match_single_device)."""
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    if jax.device_count() < 2:
        pytest.skip("needs 2 virtual devices")
    cfg = tiny_cfg(**{"train.batch_images": 2})
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)

    one = tiny_batch(rng)
    batch = {k: np.repeat(v, 2, axis=0) for k, v in one.items()}
    key = jax.random.PRNGKey(3)

    def fwd(mdl, p, b, r, c):
        return zoo.forward_train(mdl, p, b, r, c)

    s1 = create_train_state(params, tx)
    f1 = make_train_step(model, cfg, forward_fn=fwd, donate=False)
    s1b, m1 = f1(s1, batch, key)

    mesh = create_mesh("2")
    s2 = create_train_state(params, tx)
    f2 = make_train_step(model, cfg, mesh=mesh, forward_fn=fwd, donate=False)
    s2b, m2 = f2(s2, shard_batch(batch, mesh), key)

    assert np.isclose(float(m1["TotalLoss"]), float(m2["TotalLoss"]),
                      rtol=1e-4)
    l1 = jax.tree.leaves(s1b.params)
    l2 = jax.tree.leaves(s2b.params)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-5)


def test_pack_placements_gaps_and_bounds():
    """Shelf packing: every rectangle in bounds, pairwise >=1px separated."""
    shapes = [(40, 64), (20, 32), (10, 16), (5, 8), (3, 4)]
    (hc, wc), places = F.pack_placements(shapes)
    assert wc == 64
    rects = []
    for (h, w), (y, x, ph, pw) in zip(shapes, places):
        assert (ph, pw) == (h, w)
        assert 0 <= y and y + h <= hc and 0 <= x and x + w <= wc
        rects.append((y, x, h, w))
    for i in range(len(rects)):
        for j in range(i + 1, len(rects)):
            yi, xi, hi, wi = rects[i]
            yj, xj, hj, wj = rects[j]
            # grow rect i by the 1px gap; it must not intersect rect j
            sep = (yi + hi + 1 <= yj or yj + hj + 1 <= yi
                   or xi + wi + 1 <= xj or xj + wj + 1 <= xi)
            assert sep, (rects[i], rects[j])


def test_pack_levels_roundtrip(rng):
    """Canvas slices reproduce the packed tensors; gaps are zero."""
    shapes = [(16, 32), (8, 16), (4, 8)]
    tensors = [jnp.asarray(rng.randn(2, h, w, 3), jnp.float32)
               for h, w in shapes]
    canvas, places = F.pack_levels(tensors)
    total = 0.0
    for t, (y, x, h, w) in zip(tensors, places):
        np.testing.assert_array_equal(
            np.asarray(canvas[:, y:y + h, x:x + w, :]), np.asarray(t))
        total += float(jnp.sum(jnp.abs(t)))
    assert np.isclose(float(jnp.sum(jnp.abs(canvas))), total, rtol=1e-6)


def test_rpn_forward_packed_matches_per_level(rng):
    """The fused one-canvas head application == five per-level applications
    (same params; 3x3 SAME borders see zeros either way)."""
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    images = jnp.asarray(rng.randn(1, 128, 128, 3), jnp.float32)
    pyramid = jax.jit(
        lambda p, im: model.apply(p, im, method="extract"))(params, images)
    per_level = jax.jit(lambda p, pyr: model.apply(
        p, pyr, method="rpn_forward"))(params, pyramid)
    packed = jax.jit(lambda p, pyr: model.apply(
        p, pyr, method="rpn_forward_packed"))(params, pyramid)
    for lv in F.RPN_LEVELS:
        for a, b in zip(per_level[lv], packed[lv]):
            assert a.shape == b.shape, lv
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-2, atol=2e-3)


def test_forward_train_packed_vs_unpacked_rpn(rng):
    """End-to-end train loss with the packed head == per-level head."""
    from dataclasses import replace

    cfg = tiny_cfg()
    assert cfg.network.fpn_packed_rpn_head  # default on
    cfg_off = cfg.with_updates(network=replace(
        cfg.network, fpn_packed_rpn_head=False))
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    key = jax.random.PRNGKey(1)
    loss_on, _ = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, key)
    loss_off, _ = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg_off)
    )(params, batch, key)
    np.testing.assert_allclose(float(loss_on), float(loss_off),
                               rtol=1e-4, atol=1e-5)


def test_packed_head_requires_spatial_radius():
    """apply_rpn_head_packed sizes its inter-level gap from the head's
    declared SPATIAL_RADIUS (1 for RPNHead's single 3x3 conv); a head
    class that declares none fails loudly instead of silently leaking
    activations across packed levels (advisor r5)."""
    from mx_rcnn_tpu.models.rpn import RPNHead

    assert RPNHead.SPATIAL_RADIUS == 1

    class NoRadiusHead:
        def __call__(self, x):
            return x, x

    pyramid = {lv: jnp.zeros((1, 4, 4, 8)) for lv in F.RPN_LEVELS}
    with pytest.raises(ValueError, match="SPATIAL_RADIUS"):
        F.apply_rpn_head_packed(NoRadiusHead(), pyramid)
