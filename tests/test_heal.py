"""graftheal (mx_rcnn_tpu/resilience/heal.py) gates — mid-run backend loss.

graftguard (tests/test_resilience.py) pinned startup acquisition and
preemption; these gates pin the failure that still killed a run dead: the
backend dying MID-STEP, hours in. Every scenario is injected
deterministically (resilience/chaos.py) on the virtual 8-device CPU mesh
and must be survived IN-PROCESS — no crash, no operator:

- device loss at step K: the run completes on its own and its final
  params are BIT-exact (f32 CPU) vs an uninterrupted run — tree AND
  ``train.flat_params=true`` storage modes;
- double loss inside one heal window: the re-dispatch fails again and the
  second heal also succeeds (the consecutive-heal cap has headroom);
- elastic shrink: the backend comes back with 4 of 8 devices — the mesh
  is re-cut with the GLOBAL batch invariant, and the loss trajectory
  matches the uninterrupted 8-device run within the existing DP parity
  tolerances (psum reassociation only), both storage modes;
- elastic resume across topologies: an emergency save cut on 8 devices
  resumes on a 4-device mesh — the checkpoint meta sidecar converts the
  dispatch skip through the images-consumed invariant.

All tests carry the ``chaos`` marker (script/smoke_resilience.sh runs the
subset); tier-1, NOT slow.
"""

import os

import numpy as np
import pytest

from mx_rcnn_tpu.config import ResilienceConfig
from mx_rcnn_tpu.obs import open_event_log, report
from mx_rcnn_tpu.obs.events import EventLog, NullEventLog
from mx_rcnn_tpu.obs.watchdog import StallWatchdog
from mx_rcnn_tpu.parallel.partition import elastic_mesh_spec
from mx_rcnn_tpu.resilience import (
    RESUMABLE_RC,
    HealCarry,
    Healer,
    PreemptionExit,
    chaos,
)
from mx_rcnn_tpu.resilience import heal as heal_mod
from mx_rcnn_tpu.train.checkpoint import (
    checkpoint_meta,
    latest_checkpoint,
    latest_epoch,
    save_checkpoint,
)
from mx_rcnn_tpu.train.metrics import MetricBag

import _resilience_driver as driver

pytestmark = pytest.mark.chaos

#: the existing DP split-parity tolerances (tests/test_train_step.py):
#: regrouping the psum over fewer devices reassociates float sums.
LOSS_RTOL = 1e-4
PARAM_RTOL, PARAM_ATOL = 2e-3, 2e-5


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    """No injection leaks between tests (or in from the outer env)."""
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _assert_trees_bitexact(a, b):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(b)}
    assert len(la) == len(lb)
    for path, va in la:
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(lb[jax.tree_util.keystr(path)]),
            err_msg=jax.tree_util.keystr(path))


def _assert_trees_close(a, b):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(b)}
    for path, va in la:
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(lb[jax.tree_util.keystr(path)]),
            rtol=PARAM_RTOL, atol=PARAM_ATOL,
            err_msg=jax.tree_util.keystr(path))


# ---------------------------------------------------------------------------
# chaos spec: the new keys
# ---------------------------------------------------------------------------

def test_chaos_parse_heal_keys():
    spec = chaos.parse("device_lost_at_step=4 device_lost_count=2 "
                       "shrink_on_reacquire=4")
    assert spec.device_lost_at_step == 4 and spec.device_lost_count == 2
    assert spec.shrink_on_reacquire == 4 and spec.active


def test_chaos_device_loss_fires_armed_count_then_stops():
    spec = chaos.parse("device_lost_at_step=4 device_lost_count=2")
    spec.maybe_device_loss(3)  # below threshold: nothing
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        spec.maybe_device_loss(4)
    with pytest.raises(RuntimeError, match="2/2"):
        spec.maybe_device_loss(4)
    spec.maybe_device_loss(4)  # count spent: the backend stays up
    assert spec.maybe_shrink(list(range(8))) == list(range(8))
    assert chaos.parse("shrink_on_reacquire=4").maybe_shrink(
        list(range(8))) == [0, 1, 2, 3]


def test_chaos_die_at_site_must_be_registered():
    """A typo'd die_at site would arm an injection that can never fire —
    the same silent-un-testing hazard the unknown-key check closes."""
    with pytest.raises(ValueError, match="die_at site"):
        chaos.parse("die_at=checkpoint_finalze")
    assert chaos.parse("die_at=checkpoint_swap").die_at == "checkpoint_swap"


def test_chaos_die_at_fires_at_every_registered_site(monkeypatch):
    """parse() accepts any member of SITES for die_at, so fire() must
    route maybe_die at EVERY site — a validated-but-unroutable site
    would be exactly the armed-never-fires hole the validation closes."""
    import signal as _signal

    for site_name in sorted(chaos.SITES):
        calls = []
        monkeypatch.setattr(chaos.os, "kill",
                            lambda pid, sig: calls.append(sig))
        spec = chaos.parse(f"die_at={site_name}")
        fire = spec.fire  # aliased: the site name is a loop VARIABLE
        fire(site_name, step=10_000, devices=["d0"])
        assert calls == [_signal.SIGKILL], site_name


# ---------------------------------------------------------------------------
# elastic mesh re-derivation (parallel/partition.py)
# ---------------------------------------------------------------------------

def test_elastic_mesh_spec_shrinks_data_axis():
    # same-or-more devices: keep the footprint (growth is not a recovery)
    assert elastic_mesh_spec(8, 1, 8, 8) == "8x1"
    assert elastic_mesh_spec(8, 1, 16, 8) == "8x1"
    # the acceptance shrink: 8 -> 4, batch 8 divides
    assert elastic_mesh_spec(8, 1, 4, 8) == "4x1"
    # non-dividing counts drop to the largest batch divisor
    assert elastic_mesh_spec(8, 1, 3, 8) == "2x1"
    assert elastic_mesh_spec(8, 1, 1, 8) == "1x1"
    # model axis is preserved; data shrinks within what remains
    assert elastic_mesh_spec(4, 2, 4, 8) == "2x2"
    with pytest.raises(ValueError, match="model axis"):
        elastic_mesh_spec(4, 2, 1, 8)


# ---------------------------------------------------------------------------
# Healer unit behavior (hermetic: acquisition/teardown monkeypatched)
# ---------------------------------------------------------------------------

def _hermetic_healer(monkeypatch, tmp_path=None, devices=("d0", "d1"),
                     **rcfg_kw):
    monkeypatch.setattr(heal_mod, "_clear_backend_cache", lambda: None)
    monkeypatch.setattr(heal_mod, "acquire_backend",
                        lambda rcfg, elog=None: list(devices))
    elog = open_event_log(str(tmp_path)) if tmp_path is not None else None
    rcfg = ResilienceConfig(**rcfg_kw)
    return Healer(rcfg, elog=elog), elog


def test_healer_classifies_with_pr5_taxonomy(monkeypatch):
    healer, _ = _hermetic_healer(monkeypatch)
    assert healer.healable(RuntimeError("UNAVAILABLE: device lost"))
    assert healer.healable(RuntimeError("ABORTED: relay restarting"))
    assert not healer.healable(RuntimeError("INVALID_ARGUMENT: shapes"))
    assert not healer.healable(ValueError("UNAVAILABLE-looking non-RT"))
    assert not healer.healable(
        RuntimeError("XlaRuntimeError: something unclassified"))
    off, _ = _hermetic_healer(monkeypatch, heal=False)
    assert not off.healable(RuntimeError("UNAVAILABLE: device lost"))


def test_healer_live_capture_and_event(monkeypatch, tmp_path):
    healer, elog = _hermetic_healer(monkeypatch, tmp_path)
    healer.note_devices(2)
    carry = HealCarry(params={"w": np.ones(3)}, opt_state=None,
                      epoch=2, dispatch=5)
    got = healer.recover(RuntimeError("UNAVAILABLE: gone"), lambda: carry)
    elog.close()
    assert got is carry and healer.devices == ["d0", "d1"]
    assert healer.heals == 1
    (ev,) = [e for e in report.load_events(str(tmp_path))
             if e["type"] == "heal"]
    assert ev["mode"] == "live" and ev["epoch"] == 2 and ev["dispatch"] == 5
    assert ev["devices_before"] == 2 and ev["devices_after"] == 2


def test_healer_regrow_reports_against_footprint(monkeypatch, tmp_path):
    """After an 8->4 shrink, a later heal that recovers the full backend
    must report the 4->8 RE-GROW — capping at the previous (shrunken)
    session's size would log 4->4 and hide the transition."""
    healer, elog = _hermetic_healer(monkeypatch, tmp_path,
                                    devices=("a", "b", "c", "d"))
    carry = HealCarry(params={})
    healer.note_devices(8)  # nominal footprint
    healer.recover(RuntimeError("UNAVAILABLE: lost"), lambda: carry)
    healer.note_devices(4)  # the shrunken session
    healer.note_progress()
    monkeypatch.setattr(heal_mod, "acquire_backend",
                        lambda rcfg, elog=None: list("abcdefgh"))
    healer.recover(RuntimeError("UNAVAILABLE: again"), lambda: carry)
    elog.close()
    evs = [e for e in report.load_events(str(tmp_path))
           if e["type"] == "heal"]
    assert [(e["devices_before"], e["devices_after"])
            for e in evs] == [(8, 4), (4, 8)]


def test_healer_capture_failure_falls_back_to_snapshot(monkeypatch,
                                                       tmp_path):
    healer, elog = _hermetic_healer(monkeypatch, tmp_path)
    snap = HealCarry(params={"w": np.zeros(3)}, opt_state=None,
                     epoch=1, dispatch=7)
    healer.set_fallback(snap)

    def bad_capture():
        raise RuntimeError("device_get on a dead backend")

    got = healer.recover(RuntimeError("UNAVAILABLE: gone"), bad_capture)
    elog.close()
    assert got is snap
    (ev,) = [e for e in report.load_events(str(tmp_path))
             if e["type"] == "heal"]
    assert ev["mode"] == "snapshot" and ev["dispatch"] == 7


def test_healer_no_capture_no_fallback_reraises(monkeypatch):
    healer, _ = _hermetic_healer(monkeypatch)
    boom = RuntimeError("UNAVAILABLE: gone")

    def bad_capture():
        raise RuntimeError("unreadable")

    with pytest.raises(RuntimeError, match="UNAVAILABLE") as ei:
        healer.recover(boom, bad_capture)
    assert ei.value is boom


def test_healer_consecutive_cap_and_progress_rearm(monkeypatch):
    healer, _ = _hermetic_healer(monkeypatch, heal_consecutive_max=2)
    carry = HealCarry(params={})
    loss = RuntimeError("UNAVAILABLE: gone")
    for _ in range(2):
        assert healer.healable(loss)
        healer.recover(loss, lambda: carry)
    # two consecutive heals with no completed dispatch: give up...
    assert not healer.healable(loss)
    # ...unless progress happened in between — then the cap re-arms
    healer.note_progress()
    assert healer.healable(loss)


def test_healer_snapshot_cadence():
    healer = Healer(ResilienceConfig(heal_snapshot_dispatches=3))
    due = [healer.snapshot_due() for _ in range(7)]
    assert due == [False, False, True, False, False, True, False]
    assert not any(Healer(ResilienceConfig(heal_snapshot_dispatches=0))
                   .snapshot_due() for _ in range(5))


# ---------------------------------------------------------------------------
# StallWatchdog.reset after a heal (satellite fix)
# ---------------------------------------------------------------------------

def test_watchdog_reset_forgets_trailing_median():
    """After a heal the first step pays re-acquisition + a fresh compile;
    judged by the pre-loss median it would read as a stall. reset() must
    re-arm with cold-start grace instead."""
    wd = StallWatchdog(NullEventLog(), stall_factor=10.0, min_stall_s=0.05,
                       poll_s=60.0)
    for _ in range(20):
        wd.beat(0.01)  # fast steady state: threshold = 10 x 0.01 = 0.1s
    assert wd.threshold_s() == pytest.approx(0.1)
    import time

    with wd._lock:  # simulate 1s without a heartbeat (the heal window)
        wd._last_beat = time.monotonic() - 1.0
    assert wd.check()  # without reset: a (false) stall fires
    with wd._lock:
        wd._last_beat = time.monotonic() - 1.0
    wd.reset()
    # post-reset: no durations -> COLD_GRACE x min_stall_s, and the
    # heal-window gap was forgotten with the beat refresh
    assert wd.threshold_s() == pytest.approx(
        StallWatchdog.COLD_GRACE * 0.05)
    assert not wd.check()
    # pause() silences the tripwire for the heal window itself (which
    # can outlast ANY threshold while acquire_backend backs off) and is
    # lifted by reset()/beat()
    wd.pause()
    with wd._lock:
        wd._last_beat = time.monotonic() - 3600.0
    assert not wd.check()
    wd.reset()
    assert not wd.check()  # reset also refreshed the beat
    wd.pause()
    wd.beat(0.01)
    with wd._lock:
        wd._last_beat = time.monotonic() - 3600.0
    assert wd.check()  # a real heartbeat lifted the pause


def test_healer_pauses_watchdog_for_the_heal_window(monkeypatch):
    """recover() must pause BEFORE capture/re-acquisition — a backend
    outage longer than the stall threshold would otherwise fire a false
    stall dump mid-heal, before the post-heal reset ran."""
    events = []

    class _WD:
        def pause(self):
            events.append("pause")

        def reset(self):
            events.append("reset")

    monkeypatch.setattr(heal_mod, "_clear_backend_cache",
                        lambda: events.append("teardown"))
    monkeypatch.setattr(heal_mod, "acquire_backend",
                        lambda rcfg, elog=None: (events.append("acquire")
                                                 or ["d0"]))
    healer = Healer(ResilienceConfig(), watchdog=_WD())
    healer.recover(RuntimeError("UNAVAILABLE: gone"),
                   lambda: HealCarry(params={}))
    assert events == ["pause", "teardown", "acquire", "reset"]


# ---------------------------------------------------------------------------
# MetricBag carry (the healed epoch keeps pre-loss accounting)
# ---------------------------------------------------------------------------

def test_metric_bag_snapshot_restore_roundtrip():
    bag = MetricBag()
    bag.update({"TotalLoss": 2.0, "RPNAcc": 0.5})
    bag.update({"TotalLoss": 4.0})
    snap = bag.snapshot()
    other = MetricBag()
    other.restore(snap)
    other.update({"TotalLoss": 6.0})
    got = other.get()
    assert got["TotalLoss"] == pytest.approx(4.0)  # (2+4+6)/3
    assert got["RPNAcc"] == pytest.approx(0.5)
    assert "RCNNAcc" not in got  # never-seen slots stay omitted


def test_rebase_schedule_count_rewrites_integer_scalars_only():
    """Elastic resume: restored optax counters are in the saving run's
    step units — rebase must rewrite exactly the scalar integer leaves
    (optax's counts) and leave slots/params untouched."""
    import optax

    from mx_rcnn_tpu.train.optimizer import rebase_schedule_count

    tx = optax.chain(optax.clip(1.0),
                     optax.sgd(optax.linear_schedule(0.1, 0.0, 100),
                               momentum=0.9))
    params = {"w": np.ones(3, np.float32)}
    opt = tx.init(params)
    # advance the counter to the "old units" position
    for _ in range(3):
        _, opt = tx.update({"w": np.ones(3, np.float32)}, opt, params)
    rebased = rebase_schedule_count(opt, 6)
    import jax

    counts = [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(
        rebased) if np.asarray(leaf).ndim == 0
        and np.issubdtype(np.asarray(leaf).dtype, np.integer)]
    assert counts and all(int(c) == 6 for c in counts)
    # non-count leaves (momentum trace) survive untouched
    trace = [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(
        rebased) if np.asarray(leaf).shape == (3,)]
    assert trace and not np.allclose(trace[0], 0.0)


# ---------------------------------------------------------------------------
# latest_checkpoint tie-break (satellite fix)
# ---------------------------------------------------------------------------

def test_latest_checkpoint_tie_break_emergency_wins(tmp_path, caplog):
    """"0003" (boundary) and "0003d00000" (emergency at dispatch 0) carry
    the SAME progress: the emergency save must win deterministically —
    and be loadable (the old code collapsed the tie to the boundary name
    by dict-order luck, crashing when only the emergency dir existed)."""
    (tmp_path / "0003d00000").mkdir()
    assert latest_checkpoint(str(tmp_path)) == (3, 0)  # alone: emergency
    (tmp_path / "0003").mkdir()
    import logging

    with caplog.at_level(logging.INFO):
        assert latest_checkpoint(str(tmp_path)) == (3, 0)
    assert any("tie" in r.message for r in caplog.records)
    # ordering around the tie is unchanged
    (tmp_path / "0003d00001").mkdir()
    assert latest_checkpoint(str(tmp_path)) == (3, 1)
    (tmp_path / "0004").mkdir()
    assert latest_checkpoint(str(tmp_path)) == (4, None)
    assert latest_epoch(str(tmp_path)) == 4


# ---------------------------------------------------------------------------
# checkpoint meta sidecar (the elastic axis of the tree-form contract)
# ---------------------------------------------------------------------------

def test_checkpoint_meta_roundtrip_sync_and_async(tmp_path):
    from mx_rcnn_tpu.train.checkpoint import CheckpointWriter, load_checkpoint

    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    meta = {"images_per_dispatch": 8, "device_count": 8,
            "epoch": 1, "dispatch": 2}
    prefix = str(tmp_path / "ck")
    save_checkpoint(prefix, 1, tree, dispatch=2, meta=meta)
    assert checkpoint_meta(prefix, 1, 2) == meta
    assert checkpoint_meta(prefix, 1) is None  # no such checkpoint
    # the sidecar does not disturb the array restore
    loaded, _ = load_checkpoint(prefix, 1, dispatch=2,
                                template={"w": np.zeros_like(tree["w"])})
    np.testing.assert_array_equal(loaded["w"], tree["w"])

    writer = CheckpointWriter()
    try:
        writer.save(prefix, 2, tree, meta={"images_per_dispatch": 4})
    finally:
        writer.close()  # publishes: meta lands with the rename
    assert checkpoint_meta(prefix, 2) == {"images_per_dispatch": 4}
    # pre-graftheal checkpoints (no sidecar) read as None, not an error
    save_checkpoint(prefix, 3, tree)
    assert checkpoint_meta(prefix, 3) is None


# ---------------------------------------------------------------------------
# obs.report fold
# ---------------------------------------------------------------------------

def test_report_folds_heal_events(tmp_path):
    elog = open_event_log(str(tmp_path))
    elog.emit("heal", epoch=0, dispatch=2, error="UNAVAILABLE: gone",
              mode="live", downtime_s=3.5, devices_before=8,
              devices_after=8)
    elog.emit("heal", epoch=1, dispatch=0, error="UNAVAILABLE: again",
              mode="snapshot", downtime_s=1.5, devices_before=8,
              devices_after=4)
    elog.close()
    summary = report.summarize(report.load_events(str(tmp_path)))
    assert summary["heals"]["count"] == 2
    assert summary["heals"]["downtime_s"] == pytest.approx(5.0)
    assert summary["heals"]["shrinks"] == ["8->4"]
    assert "again" in summary["heals"]["last_error"]
    assert report.bench_blob(summary)["heal_count"] == 2
    assert "heal:       2 in-run recover(ies)" in report.render(summary)
    assert "shrink 8->4" in report.render(summary)


def test_heal_event_type_is_schema_legal(tmp_path):
    elog = EventLog(str(tmp_path / "e.jsonl"))
    elog.emit("heal", downtime_s=1.0)  # raises if the schema missed it
    elog.close()


# ---------------------------------------------------------------------------
# the chaos matrix: device loss at step K, heal-and-continue parity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tree_baseline(tmp_path_factory):
    """The uninterrupted mesh-1 run every device-loss gate compares
    against — computed once per module (bit-deterministic, so sharing
    costs nothing and saves a full fit per test). The FLAT gates compare
    against it too: flat storage is bit-exact vs the tree chain for this
    SGD config (the PR 4 claim, gated in tests/test_flatcore.py), so one
    baseline serves both modes — and a flat heal matching the TREE
    baseline pins recovery and interchange at once."""
    tmp = tmp_path_factory.mktemp("heal_baseline")
    old = os.environ.pop(chaos.ENV_VAR, None)  # module scope sets up
    chaos.reset()                              # before the autouse fixture
    try:
        return driver.run_fit(str(tmp / "u"), flat=False)
    finally:
        if old is not None:
            os.environ[chaos.ENV_VAR] = old


@pytest.fixture(scope="module")
def mesh8_baseline(tmp_path_factory):
    """Uninterrupted mesh-8 run (tree): (params, per-epoch metrics) —
    shared by both shrink parametrizations (same flat≡tree argument as
    tree_baseline)."""
    tmp = tmp_path_factory.mktemp("heal_baseline8")
    old = os.environ.pop(chaos.ENV_VAR, None)
    chaos.reset()
    try:
        metrics = []
        params = driver.run_fit(str(tmp / "u"), mesh="8", num_images=8,
                                epoch_metrics=metrics)
        return params, metrics
    finally:
        if old is not None:
            os.environ[chaos.ENV_VAR] = old


def _heal_run(tmp_path, monkeypatch, flat, spec, expect_heals,
              compute="f32"):
    """Run fit under the armed chaos spec: it must complete WITHOUT
    operator intervention (no exception, no restart, no crash event),
    emitting one `heal` event per injected loss. Returns (params, heals)."""
    monkeypatch.setenv(chaos.ENV_VAR, spec)
    chaos.reset()
    obs_dir = str(tmp_path / "obs_healed")
    params_h = driver.run_fit(str(tmp_path / "healed"), flat=flat,
                              obs_dir=obs_dir, compute=compute)
    events = report.load_events(obs_dir)
    heals = [e for e in events if e["type"] == "heal"]
    assert len(heals) == expect_heals, heals
    assert all(e["mode"] == "live" for e in heals)
    assert [e["type"] for e in events].count("crash") == 0
    return params_h, heals


@pytest.mark.compile_heavy
def test_heal_device_loss_double_loss_parity_tree(tmp_path, monkeypatch,
                                                  tree_baseline):
    """Device loss at step K, tree mode — armed to fire TWICE: the
    re-dispatch after the first heal fails again (double loss inside one
    heal window), the second heal also succeeds (the consecutive cap,
    default 3, has headroom), and the run still completes bit-exact.
    Strictly covers the single-loss case (which shrink[tree] below also
    exercises on the 8-wide mesh)."""
    params_h, heals = _heal_run(
        tmp_path, monkeypatch, flat=False,
        spec="device_lost_at_step=4 device_lost_count=2", expect_heals=2)
    _assert_trees_bitexact(tree_baseline, params_h)
    # loss fired before the dispatch completing step 4 (epoch 1 of 2x3,
    # dispatch 0): both captures are the last known-good position
    assert [(e["epoch"], e["dispatch"]) for e in heals] == [(1, 0), (1, 0)]


@pytest.mark.compile_heavy
def test_heal_device_loss_parity_flat(tmp_path, monkeypatch, tree_baseline):
    """Flat storage: the capture is TREE-form (FlatCore.tree_state) and
    the healed session re-cuts the buffers via the SegmentTable — still
    bit-exact vs the uninterrupted baseline (tree-mode; see the fixture:
    flat≡tree is the separately-gated PR 4 claim)."""
    params_h, _ = _heal_run(tmp_path, monkeypatch, flat=True,
                            spec="device_lost_at_step=4", expect_heals=1)
    _assert_trees_bitexact(tree_baseline, params_h)


@pytest.mark.compile_heavy
def test_heal_carry_preserves_bf16_policy(tmp_path, monkeypatch,
                                          bf16_flat_baseline):
    """graftcast across a heal: the carry is f32 tree-form (masters via
    FlatCore.tree_state — the compute shadow is derived state and is NOT
    carried), and the rebuilt session re-derives the SAME bf16 policy
    from cfg — so a healed compute_dtype=bf16 run is bit-exact vs an
    uninterrupted bf16 run (the session-scope baseline shared with
    test_resilience's kill→resume gate; the module-scope f32 tree
    baseline differs by construction)."""
    params_h, _ = _heal_run(tmp_path, monkeypatch, flat=True,
                            spec="device_lost_at_step=4", expect_heals=1,
                            compute="bf16")
    _assert_trees_bitexact(bf16_flat_baseline, params_h)


# ---------------------------------------------------------------------------
# elastic shrink: 8 -> 4 virtual devices, global batch invariant
# ---------------------------------------------------------------------------

@pytest.mark.compile_heavy
@pytest.mark.parametrize("flat", [False, True], ids=["tree", "flat"])
def test_heal_shrink_8_to_4_loss_trajectory(tmp_path, monkeypatch, flat,
                                            mesh8_baseline):
    """The backend returns with half the devices: the mesh is re-cut
    4x1, each survivor carries 2 batch rows, and the loss trajectory
    matches the uninterrupted 8-device run within the existing DP parity
    tolerances (the only difference is psum reassociation)."""
    params_u, metrics_u = mesh8_baseline

    monkeypatch.setenv(chaos.ENV_VAR,
                       "device_lost_at_step=2 shrink_on_reacquire=4")
    chaos.reset()
    metrics_h = []
    obs_dir = str(tmp_path / "obs_shrunk")
    params_h = driver.run_fit(str(tmp_path / "shrunk"), mesh="8",
                              num_images=8, flat=flat,
                              epoch_metrics=metrics_h, obs_dir=obs_dir)

    assert [e for e, _ in metrics_u] == [e for e, _ in metrics_h] == [0, 1]
    for (_, mu), (_, mh) in zip(metrics_u, metrics_h):
        for name, val in mu.items():
            assert np.isclose(val, mh[name], rtol=LOSS_RTOL, atol=1e-6), (
                name, val, mh[name])
    _assert_trees_close(params_u, params_h)

    (ev,) = [e for e in report.load_events(obs_dir) if e["type"] == "heal"]
    assert ev["devices_before"] == 8 and ev["devices_after"] == 4
    summary = report.summarize(report.load_events(obs_dir))
    assert summary["heals"]["shrinks"] == ["8->4"]


# ---------------------------------------------------------------------------
# elastic resume: an emergency save cut on 8 devices resumes on 4
# ---------------------------------------------------------------------------

@pytest.mark.compile_heavy
def test_elastic_resume_across_topologies(tmp_path, monkeypatch, caplog):
    """The on-disk half of the elastic contract: a dispatch-tagged save
    minted at 8 images/dispatch resumes on a 4-wide mesh — the meta
    sidecar converts 1 old dispatch into 2 new ones, so the epoch's
    trained prefix is skipped exactly (no image retrained or skipped)."""
    prefix = str(tmp_path / "run")
    monkeypatch.setenv(chaos.ENV_VAR, "sigterm_at_step=1")
    chaos.reset()
    with pytest.raises(PreemptionExit) as ei:
        driver.run_fit(prefix, mesh="8", num_images=16, end_epoch=1)
    assert ei.value.code == RESUMABLE_RC
    assert latest_checkpoint(prefix) == (0, 1)
    meta = checkpoint_meta(prefix, 0, 1)
    assert meta["images_per_dispatch"] == 8
    assert meta["device_count"] == 8 and meta["mesh"] == {"data": 8,
                                                          "model": 1}

    monkeypatch.delenv(chaos.ENV_VAR)
    chaos.reset()
    obs_dir = str(tmp_path / "obs_resumed")
    driver.run_fit(prefix, mesh="4", num_images=16, end_epoch=1,
                   resume="auto", obs_dir=obs_dir)
    # new topology: 4 images/dispatch, 4 dispatches/epoch; the 8 trained
    # images (1 old dispatch) become a 2-dispatch skip — telemetry shows
    # epoch 0 resuming at dispatch 2, never re-emitting 0/1
    resumed_e0 = sorted(e["batch"] for e in report.load_events(obs_dir)
                        if e["type"] == "step" and e.get("epoch") == 0
                        and "step_ms" in e)
    assert resumed_e0 == [2, 3], resumed_e0
    assert latest_epoch(prefix) == 1
    assert checkpoint_meta(prefix, 1)["images_per_dispatch"] == 4

    # Leg 3 — BOUNDARY checkpoint across topologies: resuming the
    # 4-wide epoch-1 save back on the 8-wide mesh must also read the
    # sidecar and rebase the optimizer counters (skip is 0, but the
    # schedule units changed) — the gap the skip-only gating had.
    import logging

    with caplog.at_level(logging.WARNING):
        driver.run_fit(prefix, mesh="8", num_images=16, end_epoch=2,
                       resume="auto")
    assert any("optimizer counters rebased to step 2" in r.message
               for r in caplog.records), [r.message for r in caplog.records
                                          if "rebase" in r.message]
    assert latest_epoch(prefix) == 2
    assert checkpoint_meta(prefix, 2)["images_per_dispatch"] == 8


