"""graftpulse gates (mx_rcnn_tpu/obs/health.py + train/health.py).

Unit layer: the in-graph reductions (finite counts + masked norms, flat
and tree, multi-step folding), chaos nan-injection math, HealthMonitor
cadence/tripwires/known-good capture (including the zero-added-host-sync
contract — off-cadence observes convert NOTHING), FlightRecorder ring +
EventLog integration, torn-JSONL tolerance, the env fingerprint, and the
report/ledger folds of health/anomaly events.

Integration layer (tier-1, compile_heavy + chaos): enabling
``obs.health_every`` on the tiny fit adds ZERO extra compiled
executables vs the same fit with health off (the reductions fuse into
the one train-step program), and the full nan_at_step matrix — chaos
poisons one step's gradients in-graph, the tripwire catches it, arms the
anomaly actions (event, flight dump, emergency checkpoint of the last
known-good state) and ``--resume auto`` continues BIT-exact vs an
uninterrupted run, tree AND flat storage, f32 AND bf16 compute.
"""

import json
import math
import os

import numpy as np
import pytest

import _resilience_driver as driver
from mx_rcnn_tpu.obs import env_fingerprint, open_event_log, report, run_meta_fields
from mx_rcnn_tpu.obs import ledger as perf_ledger
from mx_rcnn_tpu.obs.health import FlightRecorder, HealthMonitor, NumericsAnomaly
from mx_rcnn_tpu.resilience import chaos

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    """No injection leaks between tests (or in from the outer env)."""
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# train/health.py — the in-graph reductions
# ---------------------------------------------------------------------------

def test_finite_stats_counts_and_masked_norm():
    """One fused pass: nonfinite COUNT plus the finite-MASKED squared sum
    — the norm stays informative while a few elements overflow."""
    import jax
    import jax.numpy as jnp
    from mx_rcnn_tpu.train import health as health_mod

    x = np.array([1.0, -2.0, np.nan, np.inf, 3.0, -np.inf], np.float32)
    nf, sq = jax.jit(health_mod.finite_stats)(jnp.asarray(x))
    assert int(nf) == 3
    np.testing.assert_allclose(float(sq), 1.0 + 4.0 + 9.0, rtol=1e-6)

    # bf16 buffer: the squared sum accumulates in f32 (a bf16 square
    # saturates where the f32 accumulator does not even notice)
    big = jnp.full((8,), 256.0, jnp.bfloat16)
    nf_b, sq_b = jax.jit(health_mod.finite_stats)(big)
    assert int(nf_b) == 0
    np.testing.assert_allclose(float(sq_b), 8 * 256.0 * 256.0, rtol=1e-2)


def test_probe_buffers_and_tree_fold():
    """Flat mode probes each float dtype buffer (int groups skipped);
    tree mode folds every leaf into ONE count + ONE squared sum."""
    import jax
    import jax.numpy as jnp
    from mx_rcnn_tpu.train import health as health_mod

    bufs = {"float32": jnp.asarray([1.0, np.nan, 2.0], jnp.float32),
            "int32": jnp.arange(4, dtype=jnp.int32)}
    out = jax.jit(lambda b: health_mod.probe_buffers("grad", b))(bufs)
    assert set(out) == {"grad/float32/nf", "grad/float32/sq"}
    assert int(out["grad/float32/nf"]) == 1
    np.testing.assert_allclose(float(out["grad/float32/sq"]), 5.0)

    tree = {"a": jnp.asarray([np.nan, 1.0], jnp.float32),
            "b": {"c": jnp.asarray([2.0, np.inf], jnp.float32),
                  "n": jnp.arange(2, dtype=jnp.int32)}}
    folded = jax.jit(lambda t: health_mod.probe_tree("param", t))(tree)
    assert set(folded) == {"param/tree/nf", "param/tree/sq"}
    assert int(folded["param/tree/nf"]) == 2
    np.testing.assert_allclose(float(folded["param/tree/sq"]), 1.0 + 4.0)


def test_fold_multi_step_sums_counts_keeps_last_norms():
    """Multi-step dispatch: nonfinite counts SUM over the K scanned
    steps (a poisoned middle step must surface), norms and the loss keep
    the last row."""
    import jax.numpy as jnp
    from mx_rcnn_tpu.train import health as health_mod

    h_seq = {"grad/tree/nf": jnp.asarray([0, 5, 0], jnp.int32),
             "grad/tree/sq": jnp.asarray([1.0, 2.0, 3.0], jnp.float32),
             "loss": jnp.asarray([0.5, 0.6, 0.7], jnp.float32)}
    out = health_mod.fold_multi_step(h_seq)
    assert int(out["grad/tree/nf"]) == 5
    assert float(out["grad/tree/sq"]) == 3.0
    assert abs(float(out["loss"]) - 0.7) < 1e-7


def test_chaos_poison_grads_fires_only_at_armed_step():
    """nan_at_step's in-graph injection: NaN exactly when the optimizer
    step being produced equals the armed step; numerically identity
    otherwise; int leaves pass through untouched."""
    import jax
    import jax.numpy as jnp

    g = {"w": jnp.asarray([1.0, 2.0], jnp.float32),
         "i": jnp.arange(3, dtype=jnp.int32)}
    fn = jax.jit(lambda gr, s: chaos.poison_grads(gr, s, 3))
    hit = fn(g, jnp.asarray(2, jnp.int32))    # producing step 3: poisoned
    assert np.isnan(np.asarray(hit["w"])).all()
    np.testing.assert_array_equal(np.asarray(hit["i"]), np.arange(3))
    clean = fn(g, jnp.asarray(3, jnp.int32))  # producing step 4: identity
    np.testing.assert_array_equal(np.asarray(clean["w"]), [1.0, 2.0])


# ---------------------------------------------------------------------------
# HealthMonitor — cadence, folding, tripwires
# ---------------------------------------------------------------------------

class _Scalar:
    """Stands in for a device scalar: converting it to float IS the
    device→host pull the cadence contract meters."""

    def __init__(self, value, pulls):
        self.value = value
        self.pulls = pulls

    def __float__(self):
        self.pulls[0] += 1
        return float(self.value)


def _reading(pulls, loss=1.0, grad_sq=1.0, grad_nf=0, param_nf=0):
    return {"loss": _Scalar(loss, pulls),
            "grad/float32/nf": _Scalar(grad_nf, pulls),
            "grad/float32/sq": _Scalar(grad_sq, pulls),
            "param/float32/nf": _Scalar(param_nf, pulls),
            "param/float32/sq": _Scalar(4.0, pulls)}


def test_monitor_cadence_pulls_nothing_off_cadence(tmp_path):
    """The zero-added-host-syncs contract: observe() stores a REFERENCE;
    only every Nth dispatch converts anything to float."""
    log = open_event_log(str(tmp_path))
    mon = HealthMonitor(log, every=3)
    pulls = [0]
    assert mon.observe(_reading(pulls), epoch=0, dispatch=1) is None
    assert mon.observe(_reading(pulls), epoch=0, dispatch=2) is None
    assert pulls[0] == 0  # two dispatches, zero device pulls
    mon.observe(_reading(pulls), epoch=0, dispatch=3)
    assert pulls[0] == 5  # the one cadenced read converts the 5 scalars
    log.close()
    events = report.load_events(str(tmp_path))
    health = [e for e in events if e["type"] == "health"]
    assert len(health) == 1 and health[0]["dispatch"] == 3
    assert health[0]["nonfinite"] == {"grad/float32": 0, "param/float32": 0}
    assert abs(health[0]["norm"]["grad/float32"] - 1.0) < 1e-9
    assert health[0]["grad_norm"] == 1.0


def test_monitor_nonfinite_trips_with_actions(tmp_path):
    """A nonfinite count becomes ACTION: anomaly event, trace window,
    emergency save of the last known-good carry, flight dump, then
    NumericsAnomaly under action=abort."""

    class _Tracer:
        armed = 0

        def anomaly_window(self):
            self.armed += 1

    class _Good:
        epoch, dispatch = 0, 2

    log = open_event_log(str(tmp_path))
    tracer = _Tracer()
    recorder = FlightRecorder(str(tmp_path))
    log.attach_ring(recorder)
    saves = []
    mon = HealthMonitor(
        log, every=1, tracer=tracer, recorder=recorder,
        capture=lambda: _Good(),
        save=lambda good: saves.append(good) or "/ckpt/0000d00002")
    pulls = [0]
    mon.observe(_reading(pulls), epoch=0, dispatch=1)  # clean: good refreshed
    assert mon.good is not None
    with pytest.raises(NumericsAnomaly) as ei:
        mon.observe(_reading(pulls, grad_nf=7), epoch=0, dispatch=2)
    assert "--resume auto" in str(ei.value)
    assert tracer.armed == 1 and len(saves) == 1
    log.close()

    events = report.load_events(str(tmp_path))
    anomaly = next(e for e in events if e["type"] == "anomaly")
    assert anomaly["reasons"] == ["nonfinite:grad/float32=7"]
    assert anomaly["saved"] == "/ckpt/0000d00002"
    assert anomaly["good_dispatch"] == 2
    flight = json.load(open(os.path.join(str(tmp_path),
                                         "flight_anomaly.json")))
    assert flight["reason"] == "anomaly"
    # the dump follows the emit: the ring's tail is the anomaly itself
    assert flight["events"][-1]["type"] == "anomaly"


def test_monitor_warn_mode_and_unpolluted_windows(tmp_path):
    """action=warn reports reasons without raising — and an anomalous
    reading must NOT be folded into the trailing windows (a poisoned
    median would mask the next fault)."""
    log = open_event_log(str(tmp_path))
    mon = HealthMonitor(log, every=1, grad_factor=10.0, action="warn")
    pulls = [0]
    for i in range(HealthMonitor.MIN_GRAD_HISTORY):
        assert mon.observe(_reading(pulls, grad_sq=1.0 + 0.01 * i),
                           epoch=0, dispatch=i + 1) is None
    spike = _reading(pulls, grad_sq=1e8)  # norm 1e4 >> 10x median ~1
    reasons = mon.observe(spike, epoch=0, dispatch=99)
    assert reasons and reasons[0].startswith("grad_explode")
    # same spike again: the median did NOT absorb the anomaly
    reasons2 = mon.observe(_reading(pulls, grad_sq=1e8),
                           epoch=0, dispatch=100)
    assert reasons2 and reasons2[0].startswith("grad_explode")
    log.close()


def test_monitor_loss_zscore_and_norm_overflow(tmp_path):
    """The loss z-score wire arms after MIN_LOSS_HISTORY clean readings;
    an f32 squared-sum overflow with every element finite surfaces as
    grad_norm_overflow (the count alone cannot see it)."""
    log = open_event_log(str(tmp_path))
    mon = HealthMonitor(log, every=1, loss_z=5.0, action="warn")
    pulls = [0]
    for i in range(HealthMonitor.MIN_LOSS_HISTORY):
        assert mon.observe(_reading(pulls, loss=1.0 + 0.01 * (i % 3)),
                           epoch=0, dispatch=i + 1) is None
    reasons = mon.observe(_reading(pulls, loss=50.0), epoch=0, dispatch=20)
    assert reasons and reasons[0].startswith("loss_z")

    mon2 = HealthMonitor(log, every=1, action="warn")
    reasons = mon2.observe(_reading(pulls, grad_sq=float("inf")),
                           epoch=0, dispatch=1)
    assert reasons == ["grad_norm_overflow"]
    log.close()


def test_monitor_rejects_unknown_action(tmp_path):
    with pytest.raises(ValueError):
        HealthMonitor(open_event_log(str(tmp_path)), action="explode")


def test_monitor_skips_pin_entries(tmp_path):
    """`_pin/` entries are program-output pins (full device buffers, the
    flat-mode CPU schedule quirk — train/health.py) and must NEVER be
    pulled to host: a non-floatable pin value proves the cadenced read
    skips them."""
    class _Buffer:  # float(_Buffer()) would raise
        pass

    log = open_event_log(str(tmp_path))
    mon = HealthMonitor(log, every=1)
    pulls = [0]
    reading = _reading(pulls)
    reading["_pin/float32"] = _Buffer()
    assert mon.observe(reading, epoch=0, dispatch=1) is None
    assert mon.checks == 1
    log.close()


# ---------------------------------------------------------------------------
# FlightRecorder — the crash-time ring
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_and_buffered_events(tmp_path):
    """The ring sees every emit AT EMIT TIME — including buffered kinds
    the JSONL flush cadence has not written yet — and keeps only the
    last K. The dump is the rc!=0 artifact."""
    log = open_event_log(str(tmp_path), flush_every=10_000)
    ring = FlightRecorder(str(tmp_path / "dumps"), capacity=4)
    log.attach_ring(ring)
    for i in range(6):
        log.set_step(i)
        log.emit("step", batch=i)
    # nothing on disk yet (buffered), but the ring holds the last 4
    assert report.load_events(str(tmp_path)) == []
    snap = ring.snapshot()
    assert [e["batch"] for e in snap] == [2, 3, 4, 5]
    path = ring.dump("stall")
    payload = json.load(open(path))
    assert payload["reason"] == "stall" and payload["last_step"] == 5
    assert [e["batch"] for e in payload["events"]] == [2, 3, 4, 5]
    # repeat dumps for one reason overwrite (the log keeps full history)
    log.emit("stall", stalled_s=1.0)
    assert ring.dump("stall") == path
    assert json.load(open(path))["events"][-1]["type"] == "stall"
    log.close()


def test_flight_recorder_dump_is_best_effort(tmp_path):
    """Every dump caller sits on a failure path (watchdog thread, heal,
    the crash handler's re-raise): an unwritable obs dir must log and
    return None, never raise over the error being diagnosed."""
    target = tmp_path / "blocked"
    target.write_text("a FILE where the dump dir should go")
    ring = FlightRecorder(str(target))  # makedirs/open will fail
    ring.append({"type": "step", "step": 1})
    assert ring.dump("crash") is None


# ---------------------------------------------------------------------------
# torn JSONL tails + env fingerprint
# ---------------------------------------------------------------------------

def test_report_skips_torn_tail_with_warning(tmp_path, capsys):
    """SIGKILL mid-append leaves a partial final line: fold the intact
    prefix, warn about the tear, never raise."""
    log = open_event_log(str(tmp_path))
    log.emit("epoch", epoch=0, metrics={})
    log.close()
    with open(log.path, "a", encoding="utf-8") as fh:
        fh.write('{"type": "step", "t_wall": 17')  # torn mid-append
    events = report.load_events(str(tmp_path))
    assert len(events) == 1 and events[0]["type"] == "epoch"
    assert "torn tail" in capsys.readouterr().err


def test_ledger_skips_torn_tail_with_warning(tmp_path, capsys):
    path = str(tmp_path / "ledger.jsonl")
    perf_ledger.append_rows(path, [perf_ledger.normalize_row(
        "c4", {"img_s_per_chip": 1.0}, round_=1, sha="abc", source="test")])
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"config": "c4_r101", "img_s')
    rows = perf_ledger.load_rows(path)
    assert len(rows) == 1 and rows[0]["config"] == "c4"
    assert "torn tail" in capsys.readouterr().err


def test_env_fingerprint_in_run_meta_and_ledger_rows():
    """jax/jaxlib versions + git_dirty ride run_meta and propagate from
    a bench blob down onto every ledger row — the environment-drift
    attribution fields."""
    env = env_fingerprint()
    assert env["jax_version"] and env["jaxlib_version"]
    assert isinstance(env["git_dirty"], bool)
    meta = run_meta_fields()
    for k in ("jax_version", "jaxlib_version", "git_dirty"):
        assert meta[k] == env[k]

    blob = {"value": 2.0, "metric": "img/s/chip", "mfu": 0.3,
            "jax_version": "9.9.9", "jaxlib_version": "9.9.8",
            "git_dirty": False,
            "detail": {"c4_r101_b2": {"img_s_per_chip": 2.0}}}
    rows = perf_ledger.rows_from_artifact(blob, round_=1, sha="abc")
    assert len(rows) == 2
    for row in rows:
        assert row["jax_version"] == "9.9.9"
        assert row["jaxlib_version"] == "9.9.8"
        assert row["git_dirty"] is False


# ---------------------------------------------------------------------------
# report fold of health/anomaly events
# ---------------------------------------------------------------------------

def test_report_folds_health_and_anomaly():
    events = [
        {"type": "run_meta", "jax_version": "0.4.0", "jaxlib_version":
         "0.4.1", "git_dirty": True, "config_digest": "d" * 16},
        {"type": "health", "step": 2, "epoch": 0, "dispatch": 2,
         "loss": 1.5, "loss_z": None, "grad_norm": 3.0,
         "nonfinite": {"grad/float32": 0}},
        {"type": "health", "step": 4, "epoch": 0, "dispatch": 4,
         "loss": 1.4, "loss_z": 0.3, "grad_norm": 2.5,
         "nonfinite": {"grad/float32": 12}},
        {"type": "anomaly", "step": 4, "epoch": 0, "dispatch": 4,
         "reasons": ["nonfinite:grad/float32=12"], "loss": 1.4,
         "saved": "/ckpt/0000d00003", "flight": "/obs/flight_anomaly.json"},
    ]
    summary = report.summarize(events)
    assert summary["health"]["checks"] == 2
    assert summary["health"]["nonfinite_checks"] == 1
    assert summary["health"]["last"]["grad_norm"] == 2.5
    assert summary["anomalies"][0]["reasons"] == [
        "nonfinite:grad/float32=12"]
    assert summary["run"]["git_dirty"] is True

    text = report.render(summary)
    assert "health:     2 reading(s), 1 with nonfinites" in text
    assert "ANOMALY" in text and "0000d00003" in text

    blob = report.bench_blob(summary)
    assert blob["anomaly_count"] == 1 and blob["health_checks"] == 2
    assert blob["jax_version"] == "0.4.0" and blob["git_dirty"] is True


# ---------------------------------------------------------------------------
# integration: the tiny fit — zero extra executables, nan matrix
# ---------------------------------------------------------------------------

def _assert_trees_bitexact(a, b):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(b)}
    assert len(la) == len(lb)
    for path, va in la:
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(lb[jax.tree_util.keystr(path)]),
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.compile_heavy
def test_health_adds_zero_executables_and_zero_syncs():
    """The HLO/transfer acceptance gate, on the step itself: with
    health=True the train step is still ONE compiled executable — one
    jit cache entry, same count as health=False (the reductions fuse
    into the same program; no separate health program) — and reading
    the pulse to host compiles NOTHING further. The pure-observer claim
    (health outputs never perturb the update) is gated end to end by
    the nan matrix below: each health-ON resumed run must reach params
    BIT-exact vs a health-OFF uninterrupted baseline."""
    import jax

    from mx_rcnn_tpu.models.faster_rcnn import build_model, init_params
    from mx_rcnn_tpu.obs import compile_track
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step
    from mx_rcnn_tpu.train.optimizer import build_optimizer

    cfg = driver.tiny_config()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    batch = _tiny_batch()
    rng = jax.random.PRNGKey(11)

    step_on = make_train_step(model, cfg, donate=False, health=True)
    s_on, m_on, pulse = step_on(create_train_state(params, tx), batch, rng)
    assert step_on._cache_size() == 1

    # the cadenced device→host read of the pulse piggybacks on outputs
    # the step already produced: no compile, finite clean numbers
    with compile_track.count() as cc:
        vals = {k: float(v) for k, v in pulse.items()}
    assert cc.n == 0 and step_on._cache_size() == 1
    assert all(v == 0 for k, v in vals.items() if k.endswith("/nf"))
    assert math.isfinite(vals["loss"])
    assert vals["grad/tree/sq"] > 0 and vals["update/tree/sq"] > 0
    assert math.isfinite(float(m_on["TotalLoss"]))


def _tiny_batch():
    """One 64^2 synthetic train batch (the test_flatcore shapes)."""
    import jax.numpy as jnp

    rs = np.random.RandomState(3)
    gt = np.zeros((1, 4, 4), np.float32)
    gt[:, 0] = [8, 8, 40, 40]
    valid = np.zeros((1, 4), bool)
    valid[:, 0] = True
    classes = np.zeros((1, 4), np.int32)
    classes[:, 0] = 1
    return {
        "image": jnp.asarray(rs.randn(1, 64, 64, 3).astype(np.float32)),
        "im_info": jnp.asarray([[64, 64, 1.0]], np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }


def _nan_gate(tmp_path, monkeypatch, flat, compute, params_u):
    """The graftpulse acceptance matrix body: chaos nan_at_step=5 (2x3
    dispatch grid: dispatch 2 of epoch 1) poisons the final gradients
    in-graph; health_every=1 must catch it AT that dispatch, leave an
    anomaly event + flight dump + an emergency checkpoint of the last
    known-good state (after step 4 = epoch 1 dispatch 1), and
    ``--resume auto`` — chaos disarmed — must reach final params
    BIT-exact vs an uninterrupted run."""
    monkeypatch.setenv(chaos.ENV_VAR, "nan_at_step=5")
    chaos.reset()
    obs_dir = str(tmp_path / "obs_nan")
    prefix = str(tmp_path / "run")
    with pytest.raises(NumericsAnomaly) as ei:
        driver.run_fit(prefix, flat=flat, compute=compute,
                       obs_dir=obs_dir, health_every=1)
    assert "--resume auto" in str(ei.value)

    events = report.load_events(obs_dir)
    anomalies = [e for e in events if e["type"] == "anomaly"]
    assert len(anomalies) == 1
    a = anomalies[0]
    assert any(r.startswith("nonfinite:") for r in a["reasons"])
    assert a["good_epoch"] == 1 and a["good_dispatch"] == 1
    assert a["saved"] and a["saved"].endswith("0001d00001")
    # the four clean checks before the poisoned one folded cleanly
    health = [e for e in events if e["type"] == "health"]
    assert len(health) == 5
    assert all(v == 0 for e in health[:4]
               for v in e["nonfinite"].values())
    assert any(v > 0 for v in health[-1]["nonfinite"].values())

    # flight dumps: the anomaly ring (tail = the anomaly record) and the
    # crash dump of the aborting run
    flight = json.load(open(os.path.join(obs_dir, "flight_anomaly.json")))
    assert flight["events"][-1]["type"] == "anomaly"
    assert any(e["type"] == "health" for e in flight["events"])
    assert os.path.isfile(os.path.join(obs_dir, "flight_crash.json"))

    # the report names the anomaly (the runbook's first read)
    summary = report.summarize(events)
    assert summary["anomalies"][0]["saved"] == a["saved"]

    # resume bit-exact from the known-good step
    monkeypatch.delenv(chaos.ENV_VAR)
    chaos.reset()
    params_r = driver.run_fit(prefix, flat=flat, compute=compute,
                              resume="auto",
                              obs_dir=str(tmp_path / "obs_resumed"),
                              health_every=1)
    _assert_trees_bitexact(params_u, params_r)


@pytest.mark.compile_heavy
def test_nan_tripwire_resume_tree_f32(tmp_path, monkeypatch,
                                      tree_f32_baseline):
    _nan_gate(tmp_path, monkeypatch, flat=False, compute="f32",
              params_u=tree_f32_baseline)


@pytest.mark.compile_heavy
def test_nan_tripwire_resume_flat_f32(tmp_path, monkeypatch,
                                      flat_f32_baseline):
    """Flat storage: the poison rides the FLAT master-gradient buffers
    and the per-buffer fused reductions see it."""
    _nan_gate(tmp_path, monkeypatch, flat=True, compute="f32",
              params_u=flat_f32_baseline)


@pytest.mark.compile_heavy
def test_nan_tripwire_resume_flat_bf16(tmp_path, monkeypatch,
                                       bf16_flat_baseline):
    """The graftcast stack end to end: bf16 compute, f32 masters — the
    poisoned shadow cotangent survives master_grads' cast-up, trips, and
    the f32 tree-form emergency save resumes bit-exact."""
    _nan_gate(tmp_path, monkeypatch, flat=True, compute="bf16",
              params_u=bf16_flat_baseline)


@pytest.mark.compile_heavy
def test_nan_tripwire_resume_tree_bf16(tmp_path, monkeypatch):
    params_u = driver.run_fit(str(tmp_path / "u_tree_bf16"),
                              flat=False, compute="bf16")
    _nan_gate(tmp_path, monkeypatch, flat=False, compute="bf16",
              params_u=params_u)
