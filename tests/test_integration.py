"""End-to-end integration gate (SURVEY.md §5: "one tiny end-to-end overfit
test ... as the integration gate").

Exercises the FULL loop the unit tests can't: SyntheticDataset →
AnchorLoader → fit_detector (jitted DP train step + orbax checkpointing) →
Predictor → pred_eval → mAP. The reference's only "test" was exactly this
kind of golden run, done by hand (README mAP tables); here it is CI.

Config notes (calibrated by probing, see PERF.md/commit history):
- From-scratch profile: GroupNorm + freeze_at 0 (frozen-BN needs
  pretrained statistics — models/backbones.py).
- Small anchors: classic >=91 px anchors never fit inside a 128 px image
  (allowed_border=0 -> the RPN would receive zero labels).
- rpn_positive_overlap 0.5: at this image size only ~2 anchors/image pass
  the 0.7 rule — too sparse a signal for a short run.
- The mAP gate trains the FPN model: its 2-FC head overfits in ~100 CPU
  steps, while the C4 stage-4 head (13 convs) needs far more than a CI
  budget to rank test-time proposals (verified by probing); the C4 path is
  covered by the smoke test below plus its unit tests.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
from mx_rcnn_tpu.data.loader import TestLoader
from mx_rcnn_tpu.evaluation.tester import Predictor, pred_eval
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.tools.train import fit_detector

TINY = {
    "image.pad_shape": (128, 128),
    "image.scales": ((128, 128),),
    "network.norm": "group",
    "network.freeze_at": 0,
    "network.anchor_scales": (2, 4, 8),
    "train.rpn_positive_overlap": 0.5,
    "train.rpn_pre_nms_top_n": 512,
    "train.rpn_post_nms_top_n": 128,
    "train.batch_rois": 32,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "train.flip": False,
    "train.lr": 0.0005,
    "train.lr_step": (100,),
    "test.rpn_pre_nms_top_n": 256,
    "test.rpn_post_nms_top_n": 64,
    "test.max_per_image": 8,
    "train.fpn_rpn_pre_nms_per_level": 128,
    "test.fpn_rpn_pre_nms_per_level": 64,
}


def _dataset():
    return SyntheticDataset("train", num_images=8, image_size=128,
                            max_objects=2, min_size_frac=4, max_size_frac=2)


@pytest.mark.slow
def test_end2end_overfit_and_eval(tmp_path):
    """FPN detector overfits 8 synthetic images and finds the objects.

    Epoch budget + bar re-probed after the round-3 optimizer freeze fix
    (optax.masked was applying raw-gradient ascent to the 'frozen' stem in
    this from-scratch profile): mAP reaches 0.94 at epoch 5 and 1.0 from
    epoch 6 on (scratch probe, seed 0) — 8 epochs with a 0.5 bar leaves
    noise margin and is 43% shorter than round 2's 14-epoch gate."""
    cfg = generate_config("resnet50_fpn", "synthetic", **TINY)
    ds = _dataset()
    roidb = ds.gt_roidb()

    history = []

    def record(epoch, state, bag):
        history.append(bag.get()["TotalLoss"])

    params = fit_detector(
        cfg, roidb, prefix=str(tmp_path / "ckpt"), end_epoch=8,
        frequent=1000, epoch_callback=record, seed=0)

    assert len(history) == 8
    assert np.isfinite(history).all(), history
    assert history[-1] < history[0], history

    # Checkpoint round-trip happened (orbax wrote epoch dirs).
    assert (tmp_path / "ckpt" / "0008").exists()

    model = zoo.build_model(cfg)
    predictor = Predictor(model, params, cfg)
    loader = TestLoader(roidb, cfg, batch_size=1)
    result = pred_eval(predictor, loader, ds, thresh=0.05)
    assert result["mAP"] > 0.5, result


@pytest.mark.slow
def test_end2end_vitdet_overfit_and_eval(tmp_path):
    """ViTDet (stretch config 5) earns the same convergence proof as FPN:
    overfit 8 synthetic images, find the objects.

    Calibration (scratch probe, seed 0, AdamW preset): mAP 0.56 by epoch
    4, 0.73 by 9, 1.0 by 19 — 20 epochs with a 0.5 bar leaves noise
    margin. (With the r02-era SGD recipe this config plateaued near 0.)"""
    cfg = generate_config("vitdet_b", "synthetic", **{
        "image.pad_shape": (128, 128),
        "image.scales": ((128, 128),),
        "network.vit_dim": 48,
        "network.vit_depth": 2,
        "network.vit_heads": 4,
        "network.vit_window": 4,
        "network.anchor_scales": (2, 4, 8),
        "train.rpn_positive_overlap": 0.5,
        "train.fpn_rpn_pre_nms_per_level": 128,
        "train.rpn_post_nms_top_n": 128,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
        "train.batch_images": 1,
        "train.flip": False,
        "train.lr": 3e-4,
        "train.lr_step": (10000,),
        "test.fpn_rpn_pre_nms_per_level": 64,
        "test.rpn_post_nms_top_n": 64,
        "test.max_per_image": 8,
    })
    assert cfg.train.optimizer == "adamw"  # transformer preset applied
    ds = _dataset()
    roidb = ds.gt_roidb()
    params = fit_detector(cfg, roidb, prefix=str(tmp_path / "ckpt"),
                          end_epoch=20, frequent=1000, seed=0)
    model = zoo.build_model(cfg)
    result = pred_eval(Predictor(model, params, cfg),
                       TestLoader(roidb, cfg, batch_size=1), ds, thresh=0.05)
    assert result["mAP"] > 0.5, result


@pytest.mark.slow
def test_end2end_detr_overfit_and_eval(tmp_path):
    """DETR (stretch config 5) convergence gate.

    Recalibrated r4 (VERDICT r3 item 7: the old 8-image/150-epoch gate's
    mAP>0.25 bar was soft — probed evals wobbled 0.38–0.65). Probe on the
    SMALLER 4-image/1-object fixture (scratch_probe/detr_gate_probe.py,
    seed 0, AdamW preset lr 1e-4): mAP reaches **1.0 by epoch 25 and
    holds 1.0 through 150** (sampled every 25), loss 11.13 → 1.16 (ratio
    0.105). Gate: 100 epochs (4× the convergence point), bars mAP > 0.7
    AND final loss < 0.4 × first — a half-working matcher/loss cannot
    hold 0.7 on a fixture a correct model pins at 1.0, and the gate is
    ~4× cheaper than the old one (400 vs 1200 steps).
    NOTE: lr 3e-4+ plateaus at loss ~10.4 forever; the preset lr is
    load-bearing."""
    cfg = generate_config("detr_r50", "synthetic", **{
        "image.pad_shape": (128, 128),
        "image.scales": ((128, 128),),
        "network.detr_queries": 20,
        "network.detr_hidden": 64,
        "network.detr_heads": 4,
        "network.detr_enc_layers": 2,
        "network.detr_dec_layers": 2,
        "network.norm": "group",
        "network.freeze_at": 0,
        "train.max_gt_boxes": 8,
        "train.batch_images": 1,
        "train.flip": False,
        "test.max_per_image": 8,
    })
    # the paper-schedule preset: adamw 1e-4, drop at epoch 200 (so the
    # gate trains at constant lr without overrides)
    assert cfg.train.optimizer == "adamw" and cfg.train.lr == 1e-4
    assert cfg.train.lr_step == (200,)
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=1, min_size_frac=4, max_size_frac=2)
    roidb = ds.gt_roidb()
    history = []
    params = fit_detector(
        cfg, roidb, prefix=str(tmp_path / "ckpt"), end_epoch=100,
        frequent=10000, seed=0, checkpoint_period=50,
        epoch_callback=lambda e, s, b: history.append(
            b.get()["TotalLoss"]))
    assert history[-1] < history[0] * 0.4, (history[0], history[-1])
    model = zoo.build_model(cfg)
    result = pred_eval(Predictor(model, params, cfg),
                       TestLoader(roidb, cfg, batch_size=1), ds, thresh=0.05)
    assert result["mAP"] > 0.7, result


@pytest.mark.slow
def test_end2end_c4_smoke(tmp_path):
    """The classic C4 model through the same full loop: loader → fitted
    epochs → checkpoint → Predictor → pred_eval.

    Gate calibration (scratch probes, seed 0): at this budget TotalLoss is
    dominated by the noisy RCNN-head sampling losses (probed epochs:
    2.09, 1.38, 1.87, 2.34 — no usable ratio), but RPNLogLoss decreases
    monotonically (0.286 → 0.223 → 0.165 → 0.143). The gate therefore
    requires the RPN to actually LEARN (30% log-loss drop; probed drop is
    50%) plus no blow-up of the total — a non-learning model fails."""
    cfg = generate_config("resnet50", "synthetic",
                          **dict(TINY, **{"train.lr": 0.002}))
    ds = _dataset()
    roidb = ds.gt_roidb()
    history = []

    def record(epoch, state, bag):
        history.append(dict(bag.get()))

    params = fit_detector(cfg, roidb, prefix=str(tmp_path / "ckpt"),
                          end_epoch=3, frequent=1000, epoch_callback=record,
                          seed=0)
    assert len(history) == 3
    total = [h["TotalLoss"] for h in history]
    rpn_log = [h["RPNLogLoss"] for h in history]
    assert np.isfinite(total).all(), total
    assert total[-1] < total[0] * 2, total  # no blow-up
    assert rpn_log[-1] < rpn_log[0] * 0.7, rpn_log  # the RPN learned
    assert (tmp_path / "ckpt" / "0003").exists()

    model = zoo.build_model(cfg)
    predictor = Predictor(model, params, cfg)
    result = pred_eval(predictor, TestLoader(roidb, cfg, batch_size=1), ds,
                       thresh=0.05)
    assert "mAP" in result and np.isfinite(result["mAP"])


@pytest.mark.slow
def test_end2end_generalization_heldout(tmp_path):
    """Generalization gate (r5, VERDICT item 8): train from scratch on 64
    synthetic images, eval on 16 HELD-OUT ones (different split seed →
    disjoint images). Overfit gates can pass with memorized proposals;
    this one fails if target assignment / box decode / NMS numerics are
    subtly wrong, because the detector must rank UNSEEN proposals.

    Calibration (this machine, seed 0): passes the 0.5 floor at 4 epochs
    (the color→class mapping is learnable from any 64-image sample);
    ~8 min on CPU, hence slow-marked.
    """
    cfg = generate_config("resnet50_fpn", "synthetic", **TINY)
    train_ds = SyntheticDataset("train", num_images=64, image_size=128,
                                max_objects=2, min_size_frac=4,
                                max_size_frac=2)
    held_ds = SyntheticDataset("heldout", num_images=16, image_size=128,
                               max_objects=2, min_size_frac=4,
                               max_size_frac=2)
    params = fit_detector(
        cfg, train_ds.gt_roidb(), prefix=str(tmp_path / "ckpt"),
        end_epoch=4, frequent=1000, seed=0)
    model = zoo.build_model(cfg)
    heldout_roidb = held_ds.gt_roidb()
    result = pred_eval(Predictor(model, params, cfg),
                       TestLoader(heldout_roidb, cfg, batch_size=1),
                       held_ds, thresh=0.05)
    assert result["mAP"] > 0.5, result
