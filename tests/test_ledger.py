"""graftprof perf ledger (mx_rcnn_tpu/obs/ledger.py) gates.

Unit layer: artifact normalization (partial.json / printed line / driver
wrapper), append/load round-trip, show rendering, and the check gate's
best-prior regression math with an injected regression.

Acceptance layer (tier-1): the COMMITTED seed history — PERF_LEDGER.jsonl
backfilled from BENCH_r01–r05 — must exist, contain the known trajectory
(c4_r101_b2 peaking at 46.019 img/s / MFU 0.2811 in round 4, the r05
rc=124 outage as an error row), and `python -m mx_rcnn_tpu.obs.ledger
check` must flag an injected >10% throughput regression against it with
a non-zero exit code. stdlib-only — no jax in any of these tests.
"""

import json
import os
import subprocess
import sys

import pytest

from mx_rcnn_tpu.obs import ledger

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# normalization + round-trip
# ---------------------------------------------------------------------------

def test_rows_from_partial_json_shape(tmp_path):
    detail = {"c4": {"img_s_per_chip": 40.0, "mfu": 0.25, "step_ms": 25.0,
                     "hbm_bytes": 1.2e9, "pad_waste": 0.08,
                     "compile_s": 3.5, "n_executables": 1,
                     "reps_img_s": [40.0]},
              "bad": {"error": "RuntimeError: relay dropped"}}
    rows = ledger.rows_from_artifact(detail, round_=7, sha="cafe1234",
                                     source="partial.json")
    by_cfg = {r["config"]: r for r in rows}
    assert by_cfg["c4"]["img_s_per_chip"] == 40.0
    assert by_cfg["c4"]["mfu"] == 0.25
    assert by_cfg["c4"]["hbm_bytes"] == 1.2e9
    assert by_cfg["c4"]["compile_s"] == 3.5
    assert "reps_img_s" not in by_cfg["c4"]  # only ledger fields carry over
    assert by_cfg["c4"]["round"] == 7 and by_cfg["c4"]["git_sha"] == "cafe1234"
    assert by_cfg["bad"]["error"].startswith("RuntimeError")


def test_rows_from_driver_wrapper_and_failed_round():
    ok = {"n": 4, "rc": 0, "parsed": {
        "metric": "m", "value": 46.0, "mfu": 0.28,
        "headline_config": "c4_b2",
        "detail": {"c4_b2": {"img_s_per_chip": 46.0, "mfu": 0.28}}}}
    rows = ledger.rows_from_artifact(ok)
    assert rows[0]["config"] == "headline"
    assert rows[0]["img_s_per_chip"] == 46.0
    assert rows[0]["headline_config"] == "c4_b2"
    assert rows[1]["config"] == "c4_b2" and rows[1]["round"] == 4
    # rc=124 with no parsed output (the BENCH_r05 shape) stays visible
    dead = ledger.rows_from_artifact({"n": 5, "rc": 124, "parsed": None})
    assert dead[0]["config"] == "headline" and "rc=124" in dead[0]["error"]


def test_append_load_show_roundtrip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    assert ledger.load_rows(path) == []
    n = ledger.append_rows(path, [
        ledger.normalize_row("c4", {"img_s_per_chip": 40.0, "mfu": 0.25},
                             round_=3),
        ledger.normalize_row("c4", {"img_s_per_chip": 44.0, "mfu": 0.27},
                             round_=4),
    ])
    assert n == 2
    rows = ledger.load_rows(path)
    assert [r["round"] for r in rows] == [3, 4]
    # torn tail write (killed appender) is skipped, not fatal
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"config": "torn')
    assert len(ledger.load_rows(path)) == 2
    out = ledger.render_show(rows)
    assert "c4" in out and "40.000" in out and "0.2700" in out
    assert ledger.render_show(rows, config="nope").startswith(
        "perf ledger: no rows")


def test_check_flags_injected_regression():
    history = [
        ledger.normalize_row("c4", {"img_s_per_chip": 40.0, "mfu": 0.25},
                             round_=3),
        ledger.normalize_row("c4", {"img_s_per_chip": 44.0, "mfu": 0.27},
                             round_=4),
        ledger.normalize_row("c4", {"error": "rc=124"}, round_=5),
    ]
    # within 10% of the best prior (44.0 / 0.27): clean
    ok = [ledger.normalize_row("c4", {"img_s_per_chip": 42.0, "mfu": 0.26},
                               round_=6)]
    assert ledger.check_rows(history, ok, threshold=0.10) == []
    # >10% below best throughput: flagged, naming the best prior round
    bad = [ledger.normalize_row("c4", {"img_s_per_chip": 35.0, "mfu": 0.26},
                                round_=6)]
    problems = ledger.check_rows(history, bad, threshold=0.10)
    assert len(problems) == 1
    assert "img_s_per_chip" in problems[0] and "round 4" in problems[0]
    # an MFU-only regression is flagged independently of throughput
    bad_mfu = [ledger.normalize_row(
        "c4", {"img_s_per_chip": 44.0, "mfu": 0.20}, round_=6)]
    assert any("mfu" in p for p in
               ledger.check_rows(history, bad_mfu, threshold=0.10))
    # no prior history → first measurement IS the baseline
    fresh = [ledger.normalize_row("new_cfg", {"img_s_per_chip": 1.0},
                                  round_=6)]
    assert ledger.check_rows(history, fresh) == []
    # error candidates (failed rows) are skipped, not graded
    err = [ledger.normalize_row("c4", {"error": "boom"}, round_=6)]
    assert ledger.check_rows(history, err) == []


def test_check_never_compares_across_compute_dtypes():
    """graftcast: rows are graded only against prior rows of the SAME
    compute dtype. Pre-graftcast rows (no field) count as bf16 — the
    only dtype the repo ran before round 8."""
    history = [
        # pre-graftcast row: implicitly bf16
        ledger.normalize_row("c4", {"img_s_per_chip": 44.0, "mfu": 0.28},
                             round_=4),
        ledger.normalize_row("c4", {"img_s_per_chip": 25.0, "mfu": 0.30,
                                    "compute_dtype": "f32"}, round_=6),
    ]
    assert ledger.row_dtype(history[0]) == "bf16"
    # an f32 candidate at half the bf16 throughput is NOT a regression —
    # its bar is the f32 row, not the bf16 one
    f32_cand = [ledger.normalize_row(
        "c4", {"img_s_per_chip": 24.0, "mfu": 0.29,
               "compute_dtype": "f32"}, round_=7)]
    assert ledger.check_rows(history, f32_cand, threshold=0.10) == []
    # a bf16 candidate is graded against the bf16 best (44.0), and the
    # faster f32-relative number cannot hide the drop
    bf16_cand = [ledger.normalize_row(
        "c4", {"img_s_per_chip": 30.0, "mfu": 0.27,
               "compute_dtype": "bf16"}, round_=7)]
    problems = ledger.check_rows(history, bf16_cand, threshold=0.10)
    assert problems and "round 4" in problems[0]
    # best_prior with an explicit dtype never crosses over
    best = ledger.best_prior(history, "c4", dtype="f32")
    assert best["img_s_per_chip"][0] == 25.0


def test_check_default_splits_latest_round():
    rows = [
        ledger.normalize_row("c4", {"img_s_per_chip": 44.0}, round_=4),
        ledger.normalize_row("c4", {"img_s_per_chip": 30.0}, round_=6),
    ]
    history, candidates = ledger._latest_round_split(rows)
    assert [r["round"] for r in history] == [4]
    assert [r["round"] for r in candidates] == [6]
    assert ledger.check_rows(history, candidates)
    # unkeyed (round=None) rows are the NEWEST measurements — they must
    # be the candidate set, never silently skipped behind stale rounds
    rows.append(ledger.normalize_row("c4", {"img_s_per_chip": 28.0}))
    history, candidates = ledger._latest_round_split(rows)
    assert [r["round"] for r in candidates] == [None]
    assert len(history) == 2
    assert ledger.check_rows(history, candidates)


# ---------------------------------------------------------------------------
# the committed seed history + CLI contract (tier-1 acceptance)
# ---------------------------------------------------------------------------

def _cli(*args, ledger_path=None):
    cmd = [sys.executable, "-m", "mx_rcnn_tpu.obs.ledger"]
    if ledger_path:
        cmd += ["--ledger", ledger_path]
    return subprocess.run(cmd + list(args), cwd=REPO_ROOT,
                          capture_output=True, text=True, timeout=60)


def _seed_rows():
    """The immutable BENCH_r01–r05 backfill slice of the committed
    ledger. bench.py APPENDS future rounds to the same file by design —
    the seed gates below must stay green when a better round 6+ lands,
    so they grade only rounds 1–5."""
    rows = ledger.load_rows(ledger.default_path())
    return [r for r in rows if isinstance(r.get("round"), int)
            and r["round"] <= 5]


def test_committed_seed_history_backfilled():
    """PERF_LEDGER.jsonl at the repo root carries the BENCH_r01–r05
    backfill: the known trajectory points and the r05 outage row."""
    rows = _seed_rows()
    assert rows, "PERF_LEDGER.jsonl missing or empty at the repo root"
    best = ledger.best_prior(rows, "c4_r101_b2")
    assert best["img_s_per_chip"][0] == pytest.approx(46.019)
    assert best["img_s_per_chip"][1]["round"] == 4
    assert best["mfu"][0] == pytest.approx(0.2811)
    rounds = {r.get("round") for r in rows}
    assert {1, 2, 3, 4, 5} <= rounds
    assert any(r.get("round") == 5 and r.get("error") for r in rows)


def test_ledger_check_cli_flags_regression_against_seed(tmp_path):
    """The acceptance gate: an injected >10% throughput regression vs
    the backfilled BENCH_r01–r05 history exits non-zero through the real
    CLI; a row within tolerance exits 0. Runs against a copy of the
    committed seed slice so future appended rounds can't move the bar."""
    seed = tmp_path / "seed_ledger.jsonl"
    ledger.append_rows(str(seed), _seed_rows())

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        {"c4_r101_b2": {"img_s_per_chip": 36.0, "mfu": 0.28}}))
    proc = _cli("check", "--candidate", str(bad), ledger_path=str(seed))
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "REGRESSION" in proc.stdout and "c4_r101_b2" in proc.stdout

    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(
        {"c4_r101_b2": {"img_s_per_chip": 47.1, "mfu": 0.285}}))
    proc = _cli("check", "--candidate", str(ok), ledger_path=str(seed))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)

    # show renders the committed trajectory (the PERF.md reading aid);
    # appends never REMOVE rows, so the r3/r4 points stay present
    proc = _cli("show", "--config", "c4_r101_b2")
    assert proc.returncode == 0
    assert "46.019" in proc.stdout and "0.2811" in proc.stdout

    # default mode on the seed slice: the latest round (5) is the rc=124
    # outage — an all-error candidate set must NOT read as a green gate
    # (rc 2, not 0)
    proc = _cli("check", ledger_path=str(seed))
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "no gradable" in proc.stderr


def test_ledger_add_cli_roundtrip(tmp_path):
    led = str(tmp_path / "led.jsonl")
    src = tmp_path / "partial.json"
    src.write_text(json.dumps(
        {"cfg_a": {"img_s_per_chip": 10.0, "mfu": 0.1}}))
    proc = _cli("add", str(src), "--round", "9", ledger_path=led)
    assert proc.returncode == 0, proc.stderr
    rows = ledger.load_rows(led)
    assert rows[0]["config"] == "cfg_a" and rows[0]["round"] == 9
    assert rows[0]["git_sha"]  # stamped from .git by the CLI
