"""The lint gate: graftlint reports zero non-baselined findings live.

Two layers: the in-process API run (fast, precise failure output listing
each finding) and the real CLI invocation (pins the exit-code contract
that script/lint.sh and pre-commit rely on). Both are tier-1 — from this
PR on, introducing a host sync inside jit, a donation drop, a config
typo, or a swallowed exception fails the test suite, not a launch.
"""

import os
import subprocess
import sys

from mx_rcnn_tpu.analysis import Settings, run
from mx_rcnn_tpu.analysis import baseline as baseline_mod

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_live_tree_is_lint_clean():
    settings = Settings.load(REPO_ROOT)
    entries = baseline_mod.load(os.path.join(REPO_ROOT, settings.baseline))
    result = run(settings.paths, REPO_ROOT, settings, entries)
    assert result.files_checked > 50  # the walker actually saw the tree
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        f"graftlint found {len(result.findings)} non-baselined "
        f"finding(s):\n{rendered}\n\nFix them, or (for pre-existing debt "
        "only) adopt deliberately via "
        "`python -m mx_rcnn_tpu.analysis --write-baseline`.")


def test_baseline_has_no_stale_entries():
    settings = Settings.load(REPO_ROOT)
    entries = baseline_mod.load(os.path.join(REPO_ROOT, settings.baseline))
    result = run(settings.paths, REPO_ROOT, settings, entries)
    matcher = baseline_mod.Matcher(entries)
    for f in result.baselined:
        matcher.consume(f)
    assert not matcher.unused(), (
        "stale baseline entries (the flagged lines were fixed or edited) — "
        f"prune them: {matcher.unused()}")


def test_baseline_entries_point_at_real_code():
    """Baseline rot fails loudly: every entry's file must still exist and
    its pinned source-line text must still appear in that file. (The
    matcher-based staleness test above needs a full lint run; this one
    catches rot even for entries whose rule was disabled or whose file
    was deleted/moved — shapes the matcher never exercises.)"""
    settings = Settings.load(REPO_ROOT)
    entries = baseline_mod.load(os.path.join(REPO_ROOT, settings.baseline))
    rotten = []
    for e in entries:
        path = os.path.join(REPO_ROOT, e["path"])
        if not os.path.isfile(path):
            rotten.append(f"{e['path']}: file no longer exists "
                          f"(rule {e['rule']})")
            continue
        with open(path, "r", encoding="utf-8") as fh:
            lines = {ln.strip() for ln in fh}
        if e["text"] not in lines:
            rotten.append(f"{e['path']}: no line matches {e['text']!r} "
                          f"(rule {e['rule']})")
    assert not rotten, (
        "baseline entries pointing at code that no longer exists — "
        "regenerate with `python -m mx_rcnn_tpu.analysis "
        "--write-baseline`:\n" + "\n".join(rotten))


def test_cli_exits_zero_on_live_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "mx_rcnn_tpu.analysis",
         "mx_rcnn_tpu", "tests"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"`python -m mx_rcnn_tpu.analysis mx_rcnn_tpu tests` exited "
        f"{proc.returncode}:\n{proc.stdout}\n{proc.stderr}")
    assert "0 findings" in proc.stdout
