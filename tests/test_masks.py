"""RLE mask API (mx_rcnn_tpu/masks) vs hand-computed cases.

The reference vendors pycocotools' C maskApi (rcnn/pycocotools/maskApi.c);
pycocotools is not installed in this environment (SURVEY.md §8), so these
tests pin the format with hand-built fixtures: column-major run order, the
COCO varint/delta string codec, crowd IoU semantics.
"""

import numpy as np
import pytest

from mx_rcnn_tpu import masks


def test_encode_decode_roundtrip():
    rs = np.random.RandomState(0)
    for _ in range(10):
        m = (rs.rand(13, 7) > 0.5).astype(np.uint8)
        assert np.array_equal(masks.decode(masks.encode(m)), m)


def test_counts_column_major_order():
    # 2x3 mask, only pixel (row 1, col 0) set. Column-major flat order is
    # [m[0,0], m[1,0], m[0,1], m[1,1], m[0,2], m[1,2]] = [0,1,0,0,0,0]
    # -> runs [1, 1, 4].
    m = np.zeros((2, 3), np.uint8)
    m[1, 0] = 1
    rle = masks.encode(m)
    assert masks.decompress(rle["counts"]) == [1, 1, 4]


def test_counts_leading_one_starts_with_zero_run():
    m = np.ones((2, 2), np.uint8)
    rle = masks.encode(m)
    assert masks.decompress(rle["counts"]) == [0, 4]


def test_compress_roundtrip_various():
    cases = [
        [0, 4],
        [1, 1, 4],
        [5, 10, 3, 200, 7],
        [100000, 1, 100000],  # multi-chunk varints
        [0, 1, 0, 1, 0, 1],   # deltas go negative
    ]
    for counts in cases:
        assert masks.decompress(masks.compress(counts)) == counts


def test_compress_known_string():
    # maskApi rleToString stores the first THREE counts raw (delta only from
    # i=3): [1, 1, 4] -> chr(1+48) chr(1+48) chr(4+48) = b"114";
    # [1, 1, 4, 2] appends delta 2-1=1 -> b"1141".
    assert masks.compress([1, 1, 4]) == b"114"
    assert masks.decompress(b"114") == [1, 1, 4]
    assert masks.compress([1, 1, 4, 2]) == b"1141"
    assert masks.decompress(b"1141") == [1, 1, 4, 2]


def test_area():
    m = np.zeros((4, 4), np.uint8)
    m[1:3, 1:4] = 1
    assert masks.area(masks.encode(m)) == 6


def test_merge_union_and_intersect():
    a = np.zeros((4, 4), np.uint8)
    b = np.zeros((4, 4), np.uint8)
    a[0:2, 0:2] = 1
    b[1:3, 1:3] = 1
    union = masks.decode(masks.merge([masks.encode(a), masks.encode(b)]))
    inter = masks.decode(
        masks.merge([masks.encode(a), masks.encode(b)], intersect=True))
    assert union.sum() == 7
    assert inter.sum() == 1
    assert inter[1, 1] == 1


def test_iou_plain_and_crowd():
    a = np.zeros((4, 4), np.uint8)
    b = np.zeros((4, 4), np.uint8)
    a[0:2, 0:2] = 1  # area 4
    b[0:4, 0:2] = 1  # area 8, contains a
    ra, rb = masks.encode(a), masks.encode(b)
    plain = masks.iou([ra], [rb], [False])
    assert plain[0, 0] == pytest.approx(4 / 8)
    # Crowd gt: intersection over DETECTION area = 4/4 = 1.
    crowd = masks.iou([ra], [rb], [True])
    assert crowd[0, 0] == pytest.approx(1.0)


def test_to_bbox():
    m = np.zeros((10, 10), np.uint8)
    m[2:5, 3:9] = 1
    assert masks.to_bbox(masks.encode(m)).tolist() == [3.0, 2.0, 6.0, 3.0]


def test_poly_to_mask_rectangle():
    # Axis-aligned rectangle covering pixel centers in cols 1..3, rows 1..2.
    poly = [1.0, 1.0, 4.0, 1.0, 4.0, 3.0, 1.0, 3.0]
    m = masks.poly_to_mask(poly, 5, 6)
    want = np.zeros((5, 6), np.uint8)
    want[1:3, 1:4] = 1
    assert np.array_equal(m, want)


def test_poly_to_mask_triangle_even_odd():
    # Right triangle (0,0)-(6,0)-(0,6): pixel center (x+.5, y+.5) is inside
    # iff x + y < 5 (strictly below the hypotenuse x+y=6 sampled at centers).
    poly = [0.0, 0.0, 6.0, 0.0, 0.0, 6.0]
    m = masks.poly_to_mask(poly, 6, 6)
    for y in range(6):
        for x in range(6):
            assert m[y, x] == (1 if x + y < 5 else 0), (x, y)


def test_fr_bbox():
    rle = masks.fr_bbox([1.0, 2.0, 3.0, 2.0], 6, 6)
    m = masks.decode(rle)
    want = np.zeros((6, 6), np.uint8)
    want[2:4, 1:4] = 1
    assert np.array_equal(m, want)


def test_fr_py_objects_dispatch():
    # Polygon list form.
    r1 = masks.fr_py_objects([[1.0, 1.0, 4.0, 1.0, 4.0, 3.0, 1.0, 3.0]], 5, 6)
    assert masks.area(r1) == 6
    # Uncompressed dict form.
    r2 = masks.fr_py_objects({"size": [2, 3], "counts": [1, 1, 4]}, 2, 3)
    assert masks.decode(r2)[1, 0] == 1
    # Compressed passes through.
    r3 = masks.fr_py_objects({"size": [2, 3], "counts": b"114"}, 2, 3)
    assert np.array_equal(masks.decode(r3), masks.decode(r2))


def test_decode_rejects_bad_length():
    with pytest.raises(ValueError):
        masks.decode({"size": [2, 2], "counts": [1, 1]})


class TestPaste:
    def test_paste_identity_box(self):
        from mx_rcnn_tpu.masks.paste import paste_mask

        prob = np.zeros((4, 4), np.float32)
        prob[1:3, 1:3] = 1.0
        # Box covering exactly an 8x8 region: the 4x4 mask upsamples 2x.
        out = paste_mask(prob, [4, 4, 11, 11], 16, 16)
        assert out.shape == (16, 16)
        # Centre of the on-region maps to pixels ~(4+2*1.5 .. 4+2*2.5).
        assert out[8, 8] == 1 and out[9, 9] == 1
        assert out[4, 4] == 0 and out[12, 12] == 0
        # Nothing outside the box.
        assert out[:4].sum() == 0 and out[:, :4].sum() == 0

    def test_paste_clips_to_image(self):
        from mx_rcnn_tpu.masks.paste import paste_mask

        prob = np.ones((4, 4), np.float32)
        out = paste_mask(prob, [-5, -5, 4, 4], 8, 8)
        assert out.shape == (8, 8)
        assert out[:5, :5].all()
        assert out[6:, 6:].sum() == 0

    def test_paste_rles_roundtrip(self):
        from mx_rcnn_tpu.masks.paste import paste_masks_to_rles

        probs = np.ones((2, 4, 4), np.float32)
        boxes = np.asarray([[0, 0, 3, 3], [4, 4, 7, 7]], np.float32)
        rles = paste_masks_to_rles(probs, boxes, 8, 8)
        m0 = masks.decode(rles[0])
        m1 = masks.decode(rles[1])
        assert m0[:4, :4].all() and m0.sum() == 16
        assert m1[4:, 4:].all() and m1.sum() == 16


class TestNativeKernels:
    """Differential: C kernels (cc/maskapi.c via ctypes) vs the numpy layer."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from mx_rcnn_tpu.masks import _native
        if not _native.available():
            pytest.skip("C toolchain unavailable; numpy fallback covered "
                        "by the other tests")

    def test_encode_decode_matches_numpy(self):
        from mx_rcnn_tpu.masks import _native
        rs = np.random.RandomState(1)
        for shape in [(13, 7), (1, 1), (5, 40), (64, 48)]:
            m = (rs.rand(*shape) > 0.5).astype(np.uint8)
            counts = _native.encode_counts(m)
            flat = np.asfortranarray(m.astype(bool)).ravel(order="F")
            from mx_rcnn_tpu.masks.rle import _runs
            assert counts.tolist() == _runs(flat)
            back = _native.decode_counts(counts, *shape)
            assert np.array_equal(back, m)

    def test_merge_iou_match_dense(self):
        from mx_rcnn_tpu.masks import _native
        rs = np.random.RandomState(2)
        a = (rs.rand(20, 15) > 0.6).astype(np.uint8)
        b = (rs.rand(20, 15) > 0.4).astype(np.uint8)
        ca = _native.encode_counts(a)
        cb = _native.encode_counts(b)
        for intersect in (False, True):
            got = _native.decode_counts(
                _native.merge_counts(ca, cb, intersect), 20, 15)
            want = (a & b) if intersect else (a | b)
            assert np.array_equal(got, want.astype(np.uint8))
        got_iou = _native.iou_counts([ca], [cb], [False])[0, 0]
        inter = np.logical_and(a, b).sum()
        union = np.logical_or(a, b).sum()
        assert got_iou == pytest.approx(inter / union)
        crowd_iou = _native.iou_counts([ca], [cb], [True])[0, 0]
        assert crowd_iou == pytest.approx(inter / a.sum())

    def test_public_api_uses_native(self):
        # The dispatching public functions must agree with hand checks when
        # native is on (same assertions as the numpy tests above them).
        rs = np.random.RandomState(3)
        m = (rs.rand(9, 9) > 0.5).astype(np.uint8)
        assert np.array_equal(masks.decode(masks.encode(m)), m)
        assert masks.area(masks.encode(m)) == int(m.sum())
