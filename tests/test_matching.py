"""Auction assignment (ops/matching.py) vs scipy's Hungarian oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from mx_rcnn_tpu.ops.matching import auction_assign


def _total_cost(cost, row_to_col, matched):
    return float(sum(cost[i, c] for i, (c, m) in
                     enumerate(zip(row_to_col, matched)) if m))


@pytest.mark.parametrize("n,m", [(5, 5), (10, 4), (20, 8), (100, 30)])
def test_matches_scipy_total_cost(rng, n, m):
    for trial in range(3):
        cost = rng.rand(n, m).astype(np.float32)
        valid = np.ones(m, bool)
        r2c, matched = auction_assign(jnp.asarray(cost), jnp.asarray(valid))
        r2c, matched = np.asarray(r2c), np.asarray(matched)
        # Every valid column assigned exactly once.
        assigned_cols = r2c[matched]
        assert len(assigned_cols) == m
        assert len(set(assigned_cols.tolist())) == m
        got = _total_cost(cost, r2c, matched)
        ri, ci = linear_sum_assignment(cost)
        want = float(cost[ri, ci].sum())
        assert got == pytest.approx(want, abs=1e-2), (trial, got, want)


def test_invalid_columns_ignored(rng):
    cost = rng.rand(8, 6).astype(np.float32)
    valid = np.array([True, True, False, True, False, False])
    r2c, matched = auction_assign(jnp.asarray(cost), jnp.asarray(valid))
    r2c, matched = np.asarray(r2c), np.asarray(matched)
    assert matched.sum() == 3
    assert set(r2c[matched].tolist()) == {0, 1, 3}
    got = _total_cost(cost, r2c, matched)
    ri, ci = linear_sum_assignment(cost[:, [0, 1, 3]])
    want = float(cost[:, [0, 1, 3]][ri, ci].sum())
    assert got == pytest.approx(want, abs=1e-2)


def test_all_invalid(rng):
    cost = rng.rand(4, 3).astype(np.float32)
    r2c, matched = auction_assign(jnp.asarray(cost),
                                  jnp.zeros(3, bool))
    assert not np.asarray(matched).any()


def test_under_jit_and_adversarial(rng):
    # Near-tied costs — the eps bound must still find the optimum at the
    # test tolerance.
    cost = np.zeros((6, 6), np.float32)
    cost += rng.rand(6, 6) * 1e-2
    cost[np.arange(6), np.arange(6)] -= 1.0  # strong diagonal optimum
    r2c, matched = jax.jit(auction_assign)(jnp.asarray(cost),
                                           jnp.ones(6, bool))
    assert np.asarray(matched).all()
    got = _total_cost(cost, np.asarray(r2c), np.asarray(matched))
    ri, ci = linear_sum_assignment(cost)
    assert got == pytest.approx(float(cost[ri, ci].sum()), abs=1e-2)
