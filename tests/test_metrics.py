"""MetricBag accumulation semantics (train/metrics.py).

The reference's six EvalMetrics (rcnn/core/metric.py) keep (sum, count)
running averages printed by Speedometer; MetricBag is the lazy host-side
analog. These tests pin the family-aware slot reporting added for DETR.
"""

import numpy as np

from mx_rcnn_tpu.train.metrics import MetricBag


def test_running_means():
    bag = MetricBag()
    bag.update({"TotalLoss": 2.0, "RPNAcc": 0.5})
    bag.update({"TotalLoss": 4.0, "RPNAcc": 1.0})
    got = bag.get()
    assert got["TotalLoss"] == 3.0
    assert got["RPNAcc"] == 0.75


def test_unseen_slots_are_omitted():
    """A family that never emits a slot (DETR: no RPN, no accuracies)
    must not log zeros for it."""
    bag = MetricBag()
    bag.update({"TotalLoss": 5.0, "RCNNLogLoss": 0.7, "RCNNL1Loss": 4.3})
    got = bag.get()
    assert set(got) == {"TotalLoss", "RCNNLogLoss", "RCNNL1Loss"}
    assert "RPNAcc" not in got and "RCNNAcc" not in got


def test_empty_bag_returns_empty_dict():
    """No updates at all (empty epoch): unseen slots are omitted — the
    SAME rule as mid-training, so a fixed-key consumer that works on an
    empty epoch cannot start KeyError-ing once updates arrive."""
    bag = MetricBag()
    assert bag.get() == {}


def test_intermittent_slot_uses_per_slot_count():
    """A slot present in only some updates averages over THOSE updates
    (the reference EvalMetrics' (sum, count) pairs), not the global
    update count — no dilution."""
    bag = MetricBag()
    bag.update({"TotalLoss": 2.0, "RPNAcc": 0.5})
    bag.update({"TotalLoss": 4.0})
    got = bag.get()
    assert got["TotalLoss"] == 3.0
    assert got["RPNAcc"] == 0.5  # 0.5/1, not 0.5/2


def test_reset_clears_seen_and_sums():
    bag = MetricBag()
    bag.update({"TotalLoss": 2.0})
    bag.get()
    bag.reset()
    assert bag.get() == {}  # back to the empty-bag shape
    bag.update({"RPNLogLoss": 1.0})
    assert set(bag.get()) == {"RPNLogLoss"}


def test_lazy_drain_accepts_device_scalars():
    """update() must not force conversion; get() converts anything
    float()-able (device scalars, 0-d numpy)."""
    bag = MetricBag()
    bag.update({"TotalLoss": np.float32(1.5)})
    bag.update({"TotalLoss": np.asarray(2.5)})
    assert bag.get()["TotalLoss"] == 2.0


def test_format_is_speedometer_style():
    bag = MetricBag()
    bag.update({"TotalLoss": 1.0, "RPNAcc": 0.5})
    s = bag.format()
    assert "Train-TotalLoss=1.000000" in s
    assert "Train-RPNAcc=0.500000" in s
    assert "RCNNAcc" not in s
