"""Multi-scale training via static scale buckets (data/loader.py,
config.ImageConfig.pad_shapes).

Reference: config.TRAIN.SCALES multi-entry support in the classic lineage
(BASELINE config 3 "multi-scale"). TPU delta (documented in config.py): the
scale is sampled PER BATCH, each scale has its own static pad bucket, and
each bucket costs one extra jit compile of the train step.
"""

import numpy as np
import pytest

import jax

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
from mx_rcnn_tpu.data.loader import AnchorLoader, TestLoader, pad_shape_for
from mx_rcnn_tpu.models import zoo

TWO_SCALE = {
    "image.scales": ((96, 160), (128, 160)),
    "image.pad_shapes": ((96, 96), (128, 128)),
    "image.pad_shape": (128, 128),
    "network.norm": "group",
    "network.freeze_at": 0,
    "network.anchor_scales": (2, 4, 8),
    "train.rpn_pre_nms_top_n": 128,
    "train.rpn_post_nms_top_n": 32,
    "train.batch_rois": 16,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "train.flip": False,
    "train.fpn_rpn_pre_nms_per_level": 64,
    "test.fpn_rpn_pre_nms_per_level": 32,
    "test.rpn_pre_nms_top_n": 64,
    "test.rpn_post_nms_top_n": 16,
}


def _roidb(n=12):
    ds = SyntheticDataset("train", num_images=n, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    return ds.gt_roidb()


def test_pad_shape_for_fallback_rule():
    """An EMPTY pad_shapes falls back to pad_shape (the documented
    override path — generate_config empties the preset buckets);
    overriding scales alone must not pair with stale buckets."""
    cfg = generate_config("resnet50_fpn", "synthetic",
                          **{"image.scales": ((128, 128),),
                             "image.pad_shape": (128, 128)})
    # generate_config drops the preset buckets → empty → fallback
    assert cfg.image.pad_shapes == ()
    assert pad_shape_for(cfg, 0) == (128, 128)
    cfg2 = generate_config("resnet50_fpn", "synthetic", **TWO_SCALE)
    assert pad_shape_for(cfg2, 0) == (96, 96)
    assert pad_shape_for(cfg2, 1) == (128, 128)


def test_pad_shapes_scales_mismatch_is_loud():
    """The stale-pair trap (cfg-contract): a NON-empty pad_shapes whose
    length disagrees with scales used to silently fall back to the
    single pad_shape — scales overridden by hand next to leftover
    buckets would train under-/over-padded without a word. Now a loud
    config error; only the empty tuple is the fallback path."""
    from dataclasses import replace

    cfg = generate_config("resnet50_fpn", "synthetic", **TWO_SCALE)
    stale = cfg.with_updates(image=replace(
        cfg.image, scales=cfg.image.scales + ((160, 160),)))
    with pytest.raises(ValueError, match="entry-for-entry"):
        pad_shape_for(stale, 0)


def test_override_consistency_drops_preset_buckets():
    """generate_config: overriding pad_shape (or scales) without
    pad_shapes drops the preset buckets, so the override actually takes
    effect — even when the overridden scales count still matches the
    preset bucket count."""
    cfg = generate_config("resnet101_fpn", "coco",
                          **{"image.pad_shape": (640, 1024)})
    assert cfg.image.pad_shapes == ()
    assert pad_shape_for(cfg, 0) == (640, 1024)  # not the 1088 bucket
    # ...and the preset's (800,1333) scale must NOT survive a pad-only
    # override: it would overflow the 640 canvas mid-epoch. The canvas
    # defines the single training scale.
    assert cfg.image.scales == ((640, 1024),)
    # same-length scales override: stale buckets must not survive either
    cfg2 = generate_config("resnet101_fpn", "coco",
                           **{"image.scales": ((1000, 1666), (1200, 2000))})
    assert cfg2.image.pad_shapes == ()
    # explicit pad_shapes override still wins
    cfg3 = generate_config("resnet101_fpn", "coco",
                           **{"image.scales": ((96, 160), (128, 160)),
                              "image.pad_shapes": ((96, 96), (128, 128))})
    assert pad_shape_for(cfg3, 0) == (96, 96)


def test_fpn_presets_carry_multiscale_recipe():
    cfg = generate_config("resnet101_fpn", "coco")
    assert len(cfg.image.scales) == 2
    assert len(cfg.image.pad_shapes) == len(cfg.image.scales)
    assert cfg.image.scales[-1] == (800, 1333)  # test-time scale
    for (h, w) in cfg.image.pad_shapes:
        assert h % 32 == 0 and w % 32 == 0  # exact FPN top-down shapes


def test_loader_emits_both_buckets():
    cfg = generate_config("resnet50_fpn", "synthetic", **TWO_SCALE)
    loader = AnchorLoader(_roidb(), cfg, num_shards=1, seed=0)
    shapes = set()
    for _ in range(3):  # 3 epochs × 12 batches: both buckets certain
        for batch in loader:
            shapes.add(batch["image"].shape[1:3])
            h, w = batch["im_info"][0, :2]
            assert h <= batch["image"].shape[1]
            assert w <= batch["image"].shape[2]
            # gt boxes live inside the scaled image region
            v = batch["gt_valid"][0]
            if v.any():
                assert batch["gt_boxes"][0][v][:, 2].max() <= w
                assert batch["gt_boxes"][0][v][:, 3].max() <= h
    assert shapes == {(96, 96), (128, 128)}, shapes


def test_train_step_executes_on_both_buckets():
    """The jitted step retraces per bucket and runs on each (BASELINE
    config 3 'multi-scale' — the FPN recipe trains at ≥2 scales)."""
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = generate_config("resnet50_fpn", "synthetic", **TWO_SCALE)
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=None, donate=False,
                              forward_fn=zoo.forward_train)

    loader = AnchorLoader(_roidb(), cfg, num_shards=1, seed=0)
    seen = set()
    for batch in loader:
        shape = batch["image"].shape[1:3]
        if shape in seen:
            continue
        seen.add(shape)
        state, metrics = step_fn(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["TotalLoss"])), shape
        if len(seen) == 2:
            break
    assert len(seen) == 2, "epoch did not produce both scale buckets"


def test_orientation_aware_buckets():
    """Buckets are stored landscape-oriented and transposed for portrait
    batches; only a mixed batch pays the square cover (the r03 review's
    ~60%-wasted-FLOPs finding on square-only covers)."""
    from mx_rcnn_tpu.data.loader import resolve_pad_bucket

    cfg = generate_config("resnet101_fpn", "coco")
    assert resolve_pad_bucket(cfg, 1, [True, True]) == (832, 1344)
    assert resolve_pad_bucket(cfg, 1, [False, False]) == (1344, 832)
    assert resolve_pad_bucket(cfg, 1, [True, False]) == (1344, 1344)
    assert resolve_pad_bucket(cfg, 0, [True]) == (672, 1088)


def test_portrait_batch_is_transpose_padded():
    """A portrait image trains in the transposed bucket, not a square."""
    cfg = generate_config("resnet50_fpn", "synthetic", **dict(
        TWO_SCALE, **{"image.scales": ((96, 160),),
                      "image.pad_shapes": ((96, 160),),
                      "image.pad_shape": (160, 160)}))
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    roidb = []
    for entry in ds.gt_roidb():
        e = dict(entry)
        # crop to a portrait 128x64 canvas: transpose image_data + boxes
        e["image_data"] = entry["image_data"][:, :64]
        e["boxes"] = np.clip(entry["boxes"], 0, [63, 127, 63, 127]).astype(
            entry["boxes"].dtype)
        e["width"], e["height"] = 64, 128
        roidb.append(e)
    loader = AnchorLoader(roidb, cfg, num_shards=1, seed=0)
    batch = next(iter(loader))
    # portrait 128x64: scale = min(96/64, 160/128) = 1.25 -> 160x80,
    # padded into the TRANSPOSED (160, 96) bucket, not a 160x160 square
    assert batch["image"].shape[1:3] == (160, 96)


def test_testloader_orientation_grouped_batches():
    """batch_size>1 eval: landscape-first ordering keeps batches
    orientation-pure (rectangular buckets, not the square mixed cover);
    metas still carry original indices so detections stay aligned."""
    cfg = generate_config("resnet50_fpn", "synthetic", **dict(
        TWO_SCALE, **{"image.scales": ((96, 160),),
                      "image.pad_shapes": ((96, 160),),
                      "image.pad_shape": (160, 160)}))
    ds = SyntheticDataset("train", num_images=6, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    roidb = []
    for i, entry in enumerate(ds.gt_roidb()):
        e = dict(entry)
        if i % 2:  # alternate portrait/landscape in index order
            e["image_data"] = entry["image_data"][:, :64]
            e["boxes"] = np.clip(entry["boxes"], 0,
                                 [63, 127, 63, 127]).astype(np.float32)
            e["width"], e["height"] = 64, 128
        roidb.append(e)
    loader = TestLoader(roidb, cfg, batch_size=3)
    got = []
    seen_idx = set()
    for batch, metas in loader:
        got.append(batch["image"].shape[1:3])
        seen_idx.update(m["index"] for m in metas if m["real"])
    # interleaved input → grouped output: one pure-landscape batch
    # ((96,160) bucket) + one pure-portrait ((160,96)), no square batch
    assert sorted(got) == [(96, 160), (160, 96)], got
    assert seen_idx == set(range(6))  # every image evaluated exactly once


def test_testloader_uses_largest_scale():
    cfg = generate_config("resnet50_fpn", "synthetic", **TWO_SCALE)
    loader = TestLoader(_roidb(4), cfg, batch_size=1)
    batch, metas = next(iter(loader))
    # largest scale (128,160) on a 128px square image → scale 1.0,
    # padded to the (128,128) bucket
    assert batch["image"].shape[1:3] == (128, 128)
    assert metas[0]["scale"] == 1.0
