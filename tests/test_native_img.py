"""Native fused normalize+pad (cc/imgproc.c via data/_native_img.py).

The fused kernel matches the numpy transform_image + pad_image chain to
within a couple of f32 ulps (it multiplies by a precomputed reciprocal
where numpy divides — asserted at rtol 1e-6, NOT bit-identity); the flip
variant mirrors exactly. Skips when no C toolchain is available (the
loader then uses the numpy fallback, which the packed/loader tests
already cover).
"""

import numpy as np
import pytest

from mx_rcnn_tpu.data import _native_img
from mx_rcnn_tpu.data.image import pad_image, transform_image

MEANS = (123.68, 116.779, 103.939)
STDS = (58.393, 57.12, 57.375)

pytestmark = pytest.mark.skipif(not _native_img.available(),
                                reason="no C toolchain")


def _ref(img, pad, flip=False):
    if flip:
        img = img[:, ::-1]
    return pad_image(transform_image(img.astype(np.float32), MEANS, STDS),
                     pad)


@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_fused_matches_numpy_chain(rng, dtype):
    img = (rng.rand(37, 53, 3) * 255).astype(dtype)
    out = _native_img.normalize_pad(img, MEANS, STDS, (40, 64))
    ref = _ref(img, (40, 64))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-5)
    assert out.dtype == np.float32 and out.shape == (40, 64, 3)


def test_fused_flip_matches_numpy_flip(rng):
    img = (rng.rand(21, 33, 3) * 255).astype(np.uint8)
    out = _native_img.normalize_pad(img, MEANS, STDS, (24, 40), flip=True)
    ref = _ref(img, (24, 40), flip=True)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-5)


def test_fused_exact_fit_no_padding(rng):
    img = (rng.rand(16, 16, 3) * 255).astype(np.uint8)
    out = _native_img.normalize_pad(img, MEANS, STDS, (16, 16))
    np.testing.assert_allclose(out, _ref(img, (16, 16)), rtol=1e-6,
                               atol=1e-5)


def test_fused_rejects_oversize(rng):
    img = (rng.rand(32, 16, 3) * 255).astype(np.uint8)
    with pytest.raises(ValueError, match="exceeds"):
        _native_img.normalize_pad(img, MEANS, STDS, (16, 16))


def test_fused_noncontiguous_mmap_slice(rng, tmp_path):
    """The packed path hands a sliced mmap view — the bridge must copy
    to contiguous before the C call, not crash or corrupt."""
    big = (rng.rand(4, 64, 64, 3) * 255).astype(np.uint8)
    np.save(tmp_path / "shard.npy", big)
    arr = np.load(tmp_path / "shard.npy", mmap_mode="r")
    view = np.asarray(arr[2, :30, :40])
    out = _native_img.normalize_pad(view, MEANS, STDS, (32, 48))
    np.testing.assert_allclose(out, _ref(np.array(view), (32, 48)),
                               rtol=1e-6, atol=1e-5)
