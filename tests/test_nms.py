"""NMS vs a pure-python greedy reference (the reference's rcnn/processing/nms.py
``nms()`` semantics: sort by score, suppress IoU > thresh, inclusive widths)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.nms import nms, nms_bitmask


def py_greedy_nms(dets, thresh):
    """Reference python NMS: dets (N,5) [x1,y1,x2,y2,score] -> keep indices."""
    x1, y1, x2, y2, scores = dets[:, 0], dets[:, 1], dets[:, 2], dets[:, 3], dets[:, 4]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    order = scores.argsort()[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        inds = np.where(ovr <= thresh)[0]
        order = order[inds + 1]
    return keep


def random_dets(rng, n):
    boxes = rng.uniform(0, 80, (n, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + rng.uniform(5, 60, (n, 2))
    # Distinct scores avoid tie-order ambiguity between implementations.
    scores = rng.permutation(n).astype(np.float32) / n + 0.01
    return boxes, scores


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
@pytest.mark.parametrize("thresh", [0.3, 0.5, 0.7])
def test_matches_python_reference(rng, impl, thresh):
    boxes, scores = random_dets(rng, 60)
    valid = np.ones(60, bool)
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), thresh, 60
    )
    got = np.asarray(keep_idx)[np.asarray(keep_valid)]
    want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), thresh)
    assert got.tolist() == list(want)


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_respects_validity_mask(rng, impl):
    boxes, scores = random_dets(rng, 30)
    valid = np.zeros(30, bool)
    valid[:10] = True
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), 0.5, 30
    )
    got = set(np.asarray(keep_idx)[np.asarray(keep_valid)].tolist())
    assert got <= set(range(10))
    want = py_greedy_nms(np.hstack([boxes[:10], scores[:10, None]]), 0.5)
    assert got == set(want)


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_max_output_truncates(rng, impl):
    boxes, scores = random_dets(rng, 50)
    valid = np.ones(50, bool)
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), 0.9, 5
    )
    assert keep_idx.shape == (5,)
    want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), 0.9)[:5]
    got = np.asarray(keep_idx)[np.asarray(keep_valid)]
    assert got.tolist() == want


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_all_invalid(impl):
    boxes = jnp.zeros((8, 4))
    scores = jnp.zeros((8,))
    valid = jnp.zeros((8,), bool)
    _, keep_valid = impl(boxes, scores, valid, 0.5, 4)
    assert not np.asarray(keep_valid).any()


def test_jit_consistency(rng):
    boxes, scores = random_dets(rng, 40)
    valid = np.ones(40, bool)
    args = (jnp.array(boxes), jnp.array(scores), jnp.array(valid))
    eager = nms_bitmask(*args, 0.5, 20)
    jitted = jax.jit(lambda b, s, v: nms_bitmask(b, s, v, 0.5, 20))(*args)
    assert np.array_equal(eager[0], jitted[0])
    assert np.array_equal(eager[1], jitted[1])
