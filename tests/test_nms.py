"""NMS vs a pure-python greedy reference (the reference's rcnn/processing/nms.py
``nms()`` semantics: sort by score, suppress IoU > thresh, inclusive widths)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.nms import nms, nms_bitmask


def py_greedy_nms(dets, thresh):
    """Reference python NMS: dets (N,5) [x1,y1,x2,y2,score] -> keep indices."""
    x1, y1, x2, y2, scores = dets[:, 0], dets[:, 1], dets[:, 2], dets[:, 3], dets[:, 4]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    order = scores.argsort()[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        inds = np.where(ovr <= thresh)[0]
        order = order[inds + 1]
    return keep


def random_dets(rng, n):
    boxes = rng.uniform(0, 80, (n, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + rng.uniform(5, 60, (n, 2))
    # Distinct scores avoid tie-order ambiguity between implementations.
    scores = rng.permutation(n).astype(np.float32) / n + 0.01
    return boxes, scores


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
@pytest.mark.parametrize("thresh", [0.3, 0.5, 0.7])
def test_matches_python_reference(rng, impl, thresh):
    boxes, scores = random_dets(rng, 60)
    valid = np.ones(60, bool)
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), thresh, 60
    )
    got = np.asarray(keep_idx)[np.asarray(keep_valid)]
    want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), thresh)
    assert got.tolist() == list(want)


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_respects_validity_mask(rng, impl):
    boxes, scores = random_dets(rng, 30)
    valid = np.zeros(30, bool)
    valid[:10] = True
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), 0.5, 30
    )
    got = set(np.asarray(keep_idx)[np.asarray(keep_valid)].tolist())
    assert got <= set(range(10))
    want = py_greedy_nms(np.hstack([boxes[:10], scores[:10, None]]), 0.5)
    assert got == set(want)


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_max_output_truncates(rng, impl):
    boxes, scores = random_dets(rng, 50)
    valid = np.ones(50, bool)
    keep_idx, keep_valid = impl(
        jnp.array(boxes), jnp.array(scores), jnp.array(valid), 0.9, 5
    )
    assert keep_idx.shape == (5,)
    want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), 0.9)[:5]
    got = np.asarray(keep_idx)[np.asarray(keep_valid)]
    assert got.tolist() == want


@pytest.mark.parametrize("impl", [nms, nms_bitmask])
def test_all_invalid(impl):
    boxes = jnp.zeros((8, 4))
    scores = jnp.zeros((8,))
    valid = jnp.zeros((8,), bool)
    _, keep_valid = impl(boxes, scores, valid, 0.5, 4)
    assert not np.asarray(keep_valid).any()


def test_jit_consistency(rng):
    boxes, scores = random_dets(rng, 40)
    valid = np.ones(40, bool)
    args = (jnp.array(boxes), jnp.array(scores), jnp.array(valid))
    eager = nms_bitmask(*args, 0.5, 20)
    jitted = jax.jit(lambda b, s, v: nms_bitmask(b, s, v, 0.5, 20))(*args)
    assert np.array_equal(eager[0], jitted[0])
    assert np.array_equal(eager[1], jitted[1])


class TestBatchedNMSPallas:
    """Differential tests for the Pallas blocked-bitmask kernel
    (ops/nms_pallas.py::batched_nms) against both jnp oracles.

    Off-TPU these run the kernel in interpret mode — the same code path the
    TPU lowering traces, minus Mosaic."""

    @pytest.mark.parametrize("n", [40, 128, 200, 300])
    @pytest.mark.parametrize("thresh", [0.3, 0.7])
    def test_matches_oracles(self, rng, n, thresh):
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        boxes, scores = random_dets(rng, n)
        valid = np.ones(n, bool)
        ki, kv = batched_nms(
            jnp.array(boxes)[None], jnp.array(scores)[None],
            jnp.array(valid)[None], thresh, n)
        got = np.asarray(ki)[0][np.asarray(kv)[0]]
        want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), thresh)
        assert got.tolist() == list(want)
        # And against the jnp bitmask formulation, bitwise.
        ki2, kv2 = nms_bitmask(
            jnp.array(boxes), jnp.array(scores), jnp.array(valid), thresh, n)
        assert np.array_equal(np.asarray(ki)[0], np.asarray(ki2))
        assert np.array_equal(np.asarray(kv)[0], np.asarray(kv2))

    def test_multi_block(self, rng):
        """>1 block of 128 — exercises cross-block suppression propagation."""
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        n = 384  # 3 blocks
        boxes, scores = random_dets(rng, n)
        valid = np.ones(n, bool)
        ki, kv = batched_nms(
            jnp.array(boxes)[None], jnp.array(scores)[None],
            jnp.array(valid)[None], 0.5, 100)
        got = np.asarray(ki)[0][np.asarray(kv)[0]]
        want = py_greedy_nms(np.hstack([boxes, scores[:, None]]), 0.5)[:100]
        assert got.tolist() == list(want)

    def test_batched(self, rng):
        """Independent per-set results in one batched call."""
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        sets = [random_dets(rng, 96) for _ in range(3)]
        boxes = np.stack([b for b, _ in sets])
        scores = np.stack([s for _, s in sets])
        valid = np.ones((3, 96), bool)
        ki, kv = batched_nms(
            jnp.array(boxes), jnp.array(scores), jnp.array(valid), 0.6, 96)
        for i, (b, s) in enumerate(sets):
            got = np.asarray(ki)[i][np.asarray(kv)[i]]
            want = py_greedy_nms(np.hstack([b, s[:, None]]), 0.6)
            assert got.tolist() == list(want)

    def test_ties_stable_by_original_index(self):
        """Equal-score duplicate boxes: the earlier index wins (stable sort),
        the duplicate is suppressed."""
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10],
                          [50, 50, 60, 60]], np.float32)
        scores = np.array([0.9, 0.9, 0.8], np.float32)
        valid = np.ones(3, bool)
        ki, kv = batched_nms(
            jnp.array(boxes)[None], jnp.array(scores)[None],
            jnp.array(valid)[None], 0.5, 3)
        got = np.asarray(ki)[0][np.asarray(kv)[0]]
        assert got.tolist() == [0, 2]

    def test_validity_mask(self, rng):
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        boxes, scores = random_dets(rng, 64)
        valid = np.zeros(64, bool)
        valid[:20] = True
        ki, kv = batched_nms(
            jnp.array(boxes)[None], jnp.array(scores)[None],
            jnp.array(valid)[None], 0.5, 64)
        got = np.asarray(ki)[0][np.asarray(kv)[0]]
        want = py_greedy_nms(np.hstack([boxes[:20], scores[:20, None]]), 0.5)
        assert got.tolist() == list(want)

    def test_all_invalid(self):
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        ki, kv = batched_nms(
            jnp.zeros((1, 16, 4)), jnp.zeros((1, 16)),
            jnp.zeros((1, 16), bool), 0.5, 8)
        assert not np.asarray(kv).any()

    def test_jit_consistency(self, rng):
        from mx_rcnn_tpu.ops.nms_pallas import batched_nms

        boxes, scores = random_dets(rng, 80)
        valid = np.ones(80, bool)
        args = (jnp.array(boxes)[None], jnp.array(scores)[None],
                jnp.array(valid)[None])
        eager = batched_nms(*args, 0.5, 40)
        jitted = jax.jit(lambda b, s, v: batched_nms(b, s, v, 0.5, 40))(*args)
        assert np.array_equal(eager[0], jitted[0])
        assert np.array_equal(eager[1], jitted[1])


def test_generate_proposals_pallas_vs_xla(rng):
    """The two nms_impl paths of generate_proposals agree end-to-end."""
    from mx_rcnn_tpu.ops.anchors import anchor_grid
    from mx_rcnn_tpu.ops.proposal import generate_proposals

    h, w, a = 8, 8, 9
    anchors = jnp.asarray(anchor_grid(h, w, stride=16))
    prob = jnp.asarray(rng.rand(2, h, w, 2 * a).astype(np.float32))
    deltas = jnp.asarray((rng.randn(2, h, w, 4 * a) * 0.1).astype(np.float32))
    im_info = jnp.asarray([[120.0, 120.0, 1.0], [100.0, 110.0, 1.0]])
    kw = dict(pre_nms_top_n=200, post_nms_top_n=50, nms_thresh=0.7, min_size=4)
    r1 = generate_proposals(prob, deltas, im_info, anchors, nms_impl="pallas", **kw)
    r2 = generate_proposals(prob, deltas, im_info, anchors, nms_impl="xla", **kw)
    np.testing.assert_allclose(r1[0], r2[0], rtol=1e-6)
    assert np.array_equal(r1[1], r2[1])
    np.testing.assert_allclose(r1[2], r2[2], rtol=1e-6)


def test_generate_proposals_approx_topk(rng):
    """network.proposal_topk="approx" (lax.approx_max_k): same contract,
    and at sizes where the reduction is exact, identical results."""
    from mx_rcnn_tpu.ops.anchors import anchor_grid
    from mx_rcnn_tpu.ops.proposal import generate_proposals

    h, w, a = 8, 8, 9
    anchors = jnp.asarray(anchor_grid(h, w, stride=16))
    prob = jnp.asarray(rng.rand(2, h, w, 2 * a).astype(np.float32))
    deltas = jnp.asarray((rng.randn(2, h, w, 4 * a) * 0.1).astype(np.float32))
    im_info = jnp.asarray([[120.0, 120.0, 1.0], [100.0, 110.0, 1.0]])
    kw = dict(pre_nms_top_n=200, post_nms_top_n=50, nms_thresh=0.7, min_size=4)
    ex = generate_proposals(prob, deltas, im_info, anchors,
                            topk_impl="exact", **kw)
    ap = generate_proposals(prob, deltas, im_info, anchors,
                            topk_impl="approx", **kw)
    if jax.default_backend() == "cpu":
        # On CPU the approximate reduction degenerates to exact; on real
        # TPU recall_target=0.95 only bounds tail MEMBERSHIP, so equality
        # would flake there — assert the full contract only where exact.
        np.testing.assert_allclose(ap[0], ex[0], rtol=1e-6)
        assert np.array_equal(ap[1], ex[1])
        np.testing.assert_allclose(ap[2], ex[2], rtol=1e-6)
    else:
        # Recall bound: ≥90% of the exact kept rois appear in the approx
        # set (50 kept from 200 candidates; tail misses only).
        kept_ex = {tuple(np.round(r, 3)) for r in np.asarray(ex[0][0])[np.asarray(ex[1][0])]}
        kept_ap = {tuple(np.round(r, 3)) for r in np.asarray(ap[0][0])[np.asarray(ap[1][0])]}
        assert len(kept_ex & kept_ap) >= 0.9 * len(kept_ex)
    with pytest.raises(ValueError, match="topk_impl"):
        generate_proposals(prob, deltas, im_info, anchors,
                           topk_impl="bogus", **kw)
