"""graftscope (mx_rcnn_tpu/obs) gates.

Unit layer: JSONL schema round-trip, StepTimer phase splits on a fake
loader, watchdog stall detection, report aggregation over a synthetic
event log, and the disabled sink's zero-event / zero-drain contract.

Integration layer (tier-1, compile_heavy): a short synthetic
``fit_detector`` run with obs enabled must produce a foldable event
stream — run_meta, per-step timing, epoch, checkpoint — and
``python -m mx_rcnn_tpu.obs.report`` must fold it into throughput +
compile-count fields; with obs disabled no file is written and the
MetricBag lazy-drain discipline is untouched.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs import (
    EVENT_TYPES,
    EventLog,
    NullEventLog,
    StallWatchdog,
    StepTimer,
    compile_track,
    event_log_path,
    obs_from_config,
    open_event_log,
    run_meta_fields,
)
from mx_rcnn_tpu.obs import report
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.metrics import MetricBag

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# EventLog
# ---------------------------------------------------------------------------

def test_event_log_schema_roundtrip(tmp_path):
    """One record of every type survives the JSONL round trip with the
    common stamps (wall/monotonic time, process, step) and its payload —
    including numpy scalars/arrays, which must land as plain JSON."""
    log = open_event_log(str(tmp_path), process_index=0)
    for i, t in enumerate(EVENT_TYPES):
        log.set_step(i)
        log.emit(t, payload=i, np_scalar=np.float32(1.5),  # graftlint: disable=obs-event-schema — iterating the schema itself
                 np_arr=np.arange(3))
    log.close()
    events = report.load_events(str(tmp_path))
    assert [e["type"] for e in events] == list(EVENT_TYPES)
    for i, e in enumerate(events):
        assert e["step"] == i and e["process"] == 0
        assert e["t_wall"] > 0 and e["t_mono"] > 0
        assert e["payload"] == i
        assert e["np_scalar"] == 1.5
        assert e["np_arr"] == [0, 1, 2]


def test_event_log_rejects_unknown_type(tmp_path):
    sink = EventLog(str(tmp_path / "e.jsonl"))
    with pytest.raises(ValueError, match="unknown event type"):
        sink.emit("not_a_type")
    sink.close()


def test_event_log_buffers_steps_flushes_critical(tmp_path):
    """step records buffer up to flush_every; stall/crash-class records
    hit disk immediately (they must survive the hang they diagnose)."""
    path = str(tmp_path / "e.jsonl")
    log = EventLog(path, flush_every=64)

    def lines():
        with open(path) as fh:
            return sum(1 for _ in fh)

    log.emit("step", step_ms=1.0)
    log.emit("step", step_ms=1.0)
    assert lines() == 0  # still buffered
    log.emit("stall", waited_s=9.0)
    assert lines() == 3  # critical record flushed the buffer with it
    log.close()
    assert lines() == 3


def test_event_log_path_per_process(tmp_path):
    # grafttower naming: every host (process 0 included) is a peer
    # stream of the fleet merge.
    assert event_log_path(str(tmp_path)).endswith("events_p0.jsonl")
    assert event_log_path(str(tmp_path), 3).endswith("events_p3.jsonl")


def test_run_meta_fields_digest_and_versions():
    cfg = generate_config("resnet50", "synthetic")
    fields = run_meta_fields(cfg, tool="test")
    assert len(fields["config_digest"]) == 16
    assert fields["network"] == "resnet50" and fields["tool"] == "test"
    assert "jax_version" in fields
    # digest tracks the config
    cfg2 = generate_config("resnet50", "synthetic",
                           **{"train.lr": 0.5})
    assert run_meta_fields(cfg2)["config_digest"] != fields["config_digest"]


def test_null_sink_is_inert(tmp_path):
    """The disabled sink touches nothing: no files, no state, and
    obs_from_config returns it without reading obs.dir."""
    n = NullEventLog()
    n.emit("step", step_ms=1.0)
    n.set_step(5)
    n.flush()
    n.close()
    assert n.step == 0 and n.path is None
    cfg = generate_config("resnet50", "synthetic",
                          **{"obs.dir": str(tmp_path / "never")})
    sink = obs_from_config(cfg)
    assert isinstance(sink, NullEventLog)
    assert not (tmp_path / "never").exists()
    assert os.listdir(tmp_path) == []


# ---------------------------------------------------------------------------
# StepTimer
# ---------------------------------------------------------------------------

def _slow_loader(n, wait_s):
    for i in range(n):
        time.sleep(wait_s)
        yield {"image": np.zeros((1, 4, 4, 3), np.float32), "i": i}


def test_step_timer_phase_split(tmp_path):
    """Each iteration over a fake loader emits a step event whose
    data_wait_ms reflects the loader's sleep, with dispatch_ms marked at
    the dispatched() call and step_ms covering the whole iteration."""
    log = open_event_log(str(tmp_path))
    timer = StepTimer(log)
    seen = []
    for i, batch in timer.iterate(0, _slow_loader(3, wait_s=0.02)):
        seen.append((i, batch["i"]))
        time.sleep(0.01)
        timer.dispatched()
    log.close()
    assert seen == [(0, 0), (1, 1), (2, 2)]
    steps = [e for e in report.load_events(str(tmp_path))
             if e["type"] == "step"]
    assert len(steps) == 3
    for n, e in enumerate(steps):
        assert e["step"] == n + 1  # global counter advanced per iteration
        assert e["epoch"] == 0 and e["batch"] == n
        assert e["data_wait_ms"] >= 15.0  # the 20 ms loader sleep
        assert e["dispatch_ms"] >= 8.0  # the 10 ms "dispatch"
        assert e["step_ms"] >= e["data_wait_ms"] + e["dispatch_ms"] - 1.0
    assert timer.total_steps == 3


def test_step_timer_disabled_is_passthrough_and_lazy():
    """With the null sink, iterate degrades to enumerate (same objects,
    zero events) and never drains a MetricBag — the lazy-drain
    discipline (train/metrics.py) is untouched, i.e. no per-step host
    sync is added by instrumentation."""
    timer = StepTimer(NullEventLog())
    batches = [{"x": 1}, {"x": 2}]
    bag = MetricBag()
    out = []
    for i, batch in timer.iterate(0, batches):
        bag.update({"TotalLoss": 1.0})
        timer.dispatched()
        out.append((i, batch))
    assert out == [(0, batches[0]), (1, batches[1])]
    assert out[0][1] is batches[0]  # identity: no copies, no wrapping
    assert len(bag._pending) == 2  # nothing forced a drain
    assert timer.total_steps == 0


# ---------------------------------------------------------------------------
# Speedometer emission
# ---------------------------------------------------------------------------

def test_speedometer_logs_and_emits(tmp_path):
    log = open_event_log(str(tmp_path))
    meter = Speedometer(batch_size=2, frequent=2, event_log=log)
    bag = MetricBag()
    bag.update({"TotalLoss": 1.0})
    assert meter(0, 0, bag) is None
    speed = meter(0, 1, bag)
    assert speed is not None and speed > 0
    log.close()
    windows = [e for e in report.load_events(str(tmp_path))
               if e["type"] == "step" and "samples_per_sec" in e]
    assert len(windows) == 1
    assert windows[0]["window"] == 2
    assert windows[0]["samples_per_sec"] == pytest.approx(speed, rel=1e-3)


# ---------------------------------------------------------------------------
# StallWatchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_on_stall_with_stacks(tmp_path):
    """An artificially stalled step trips the watchdog exactly once per
    episode, and the stall event carries this (main) thread's stack."""
    log = open_event_log(str(tmp_path))
    wd = StallWatchdog(log, stall_factor=2.0, min_stall_s=0.05, poll_s=10)
    for _ in range(5):
        wd.beat(0.01)
    assert wd.threshold_s() == pytest.approx(0.05)  # min_stall_s floor
    now = time.monotonic()
    assert not wd.check(now)  # fresh heartbeat: no stall
    assert wd.check(now + 1.0)  # stalled
    assert not wd.check(now + 2.0)  # one event per episode
    wd.beat(0.01)  # heartbeat re-arms the tripwire
    assert wd.check(time.monotonic() + 1.0)
    log.close()
    stalls = [e for e in report.load_events(str(tmp_path))
              if e["type"] == "stall"]
    assert len(stalls) == 2
    assert stalls[0]["waited_s"] >= 0.9
    assert stalls[0]["median_step_s"] == pytest.approx(0.01)
    assert any("test_obs" in stack or "MainThread" in name
               for name, stack in stalls[0]["stacks"].items())


def test_watchdog_threshold_scales_with_median():
    wd = StallWatchdog(NullEventLog(), stall_factor=10.0, min_stall_s=1.0)
    # pre-first-step: cold-start grace (compiles are slow, not stalls)
    assert wd.threshold_s() == pytest.approx(
        StallWatchdog.COLD_GRACE * 1.0)
    for d in (0.2, 0.3, 0.4):
        wd.beat(d)
    assert wd.threshold_s() == pytest.approx(3.0)  # 10 x median(0.3)


def test_watchdog_thread_emits(tmp_path):
    """The real daemon thread path: a stalled 'run' produces a stall
    event on disk without any synchronous check() calls."""
    log = open_event_log(str(tmp_path))
    wd = StallWatchdog(log, stall_factor=2.0, min_stall_s=0.05,
                       poll_s=0.02)
    wd.beat(0.01)  # one completed step arms the steady-state threshold
    wd.start()
    try:
        time.sleep(0.3)  # no further beats: stalled from here on
    finally:
        wd.stop()
    log.close()
    assert any(e["type"] == "stall"
               for e in report.load_events(str(tmp_path)))


# ---------------------------------------------------------------------------
# graftprof: cost accounting (obs/costs.py)
# ---------------------------------------------------------------------------

def test_executable_costs_vs_hand_count(tmp_path):
    """FLOP/HBM extraction on a tiny jitted matmul vs hand-counted
    values: a 64x64 @ 64x64 product is 2·64³ FLOPs (+ the sum's
    epsilon), one 16 KiB input, one f32 scalar output."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.obs import costs

    compiled = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.ones((64, 64), jnp.float32)).compile()
    c = costs.executable_costs(compiled)
    hand = 2 * 64 ** 3
    assert abs(c["flops"] - hand) / hand < 0.05
    assert c["hbm_args"] == 64 * 64 * 4
    assert c["hbm_output"] == 4
    assert c["hbm_bytes"] >= c["hbm_args"] + c["hbm_output"]
    assert c["bytes_accessed"] > 0

    # mfu_from: analytic flops x measured rate / peak (and the guards)
    assert costs.mfu_from(hand, 100.0, peak_flops=float(hand) * 1000
                          ) == pytest.approx(0.1)
    assert costs.mfu_from(None, 100.0) is None
    assert costs.mfu_from(hand, 0.0) is None


def test_batch_pad_waste_fraction():
    """real pixels ÷ canvas pixels from im_info — plain and
    multi-step-stacked batches; malformed batches degrade to {}."""
    from mx_rcnn_tpu.obs import costs

    batch = {"image": np.zeros((2, 64, 64, 3), np.float32),
             "im_info": np.asarray([[32, 64, 1.0], [64, 64, 1.0]],
                                   np.float32)}
    pw = costs.batch_pad_waste(batch)
    assert pw["canvas"] == [64, 64]
    assert pw["pad_waste"] == pytest.approx(
        1 - (32 * 64 + 64 * 64) / (2 * 64 * 64))
    # stacked (K, B, ...) leaves flatten
    stacked = {"image": np.zeros((2, 2, 64, 64, 3), np.float32),
               "im_info": np.tile(batch["im_info"], (2, 1, 1))}
    assert costs.batch_pad_waste(stacked)["pad_waste"] == pw["pad_waste"]
    assert costs.step_fields(batch) == {"canvas": [64, 64],
                                        "pad_waste": pw["pad_waste"]}
    assert costs.batch_pad_waste({"no": "contract"}) == {}
    assert costs.step_fields({"no": "contract"}) == {}


def test_loader_pad_waste_counters():
    """AnchorLoader accumulates real/canvas pixel counters per batch
    (from worker threads — the graftprof canvas-packing baseline):
    128px synthetic images on a 256-pad canvas waste exactly 75%."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.data.loader import AnchorLoader

    cfg = generate_config("resnet50", "synthetic", **{
        "image.pad_shape": (256, 256), "image.scales": ((128, 256),),
        "train.batch_images": 2, "train.flip": False,
        "train.max_gt_boxes": 4})
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    loader = AnchorLoader(ds.gt_roidb(), cfg, num_shards=1)
    assert loader.pad_waste_stats() is None  # nothing assembled yet
    with loader:
        n = sum(1 for _ in loader)
    stats = loader.pad_waste_stats()
    assert n == 2 and stats["batches"] == 2
    assert stats["real_px"] == 4 * 128 * 128
    assert stats["canvas_px"] == 4 * 256 * 256
    assert stats["pad_waste"] == pytest.approx(0.75)


# ---------------------------------------------------------------------------
# graftprof: trace windows (obs/profile.py)
# ---------------------------------------------------------------------------

def test_trace_controller_step_window(tmp_path):
    """obs.trace_at_step semantics: the window opens before step K,
    closes trace_steps completed steps later, and the closed window
    emits a `trace` event with the coarse phase summary."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.obs.profile import TraceController, summarize_trace

    log = open_event_log(str(tmp_path))
    tc = TraceController(log, str(tmp_path / "trace"),
                         trace_at_step=2, trace_steps=1)
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((64, 64))
    for step in range(1, 5):
        tc.before_step(step)  # window opens BEFORE step K, so K=1 works
        f(x).block_until_ready()
        tc.step_completed(step)
    tc.close()
    log.close()
    traces = [e for e in report.load_events(str(tmp_path))
              if e["type"] == "trace"]
    assert len(traces) == 1  # one window per arming
    assert traces[0]["reason"] == "step 2"
    summary = traces[0]["summary"]
    assert summary is not None and summary["events"] > 0
    assert summary["total_ms"] >= 0
    assert set(summary["phases"]) <= {"forward", "backward", "update",
                                      "host", "infra"}
    # the summarizer is reusable on the saved dir, and honest about
    # a dir with no capture
    assert summarize_trace(traces[0]["dir"]) is not None
    assert summarize_trace(str(tmp_path / "nowhere")) is None


def test_watchdog_stall_arms_trace_window(tmp_path):
    """The stall tripwire opens ONE trace window before dumping stacks;
    the next completed step closes it into a `trace` event."""
    from mx_rcnn_tpu.obs.profile import TraceController

    log = open_event_log(str(tmp_path))
    tc = TraceController(log, str(tmp_path / "trace"))
    wd = StallWatchdog(log, stall_factor=2.0, min_stall_s=0.01,
                       poll_s=10, tracer=tc)
    wd.beat(0.005)
    assert wd.check(time.monotonic() + 1.0)  # stall → window opens
    wd.beat(0.005)
    assert wd.check(time.monotonic() + 1.0)  # second stall: window spent
    tc.step_completed(1)  # heartbeat after recovery closes the window
    tc.close()
    log.close()
    events = report.load_events(str(tmp_path))
    traces = [e for e in events if e["type"] == "trace"]
    assert len(traces) == 1 and traces[0]["reason"] == "stall"
    # ordering: the window opened before the stall record was written
    types = [e["type"] for e in events]
    assert types.index("stall") < types.index("trace")


# ---------------------------------------------------------------------------
# Compile tracking
# ---------------------------------------------------------------------------

def test_compile_tracker_emits_with_shape_signature(tmp_path):
    import jax

    log = open_event_log(str(tmp_path))
    assert compile_track.activate(log)
    try:
        compile_track.note_batch(
            {"image": np.zeros((1, 6, 11, 3), np.float32)})
        jax.jit(lambda x: x * 2.5 + 1.25)(np.ones((2, 3), np.float32))
    finally:
        compile_track.deactivate()
    log.close()
    compiles = [e for e in report.load_events(str(tmp_path))
                if e["type"] == "compile"]
    backend = [e for e in compiles if e["phase"] == "backend_compile"]
    assert backend, compiles  # tiny kernels are below the persistent-
    # cache threshold, so the jit above really XLA-compiles every run
    assert backend[0]["duration_ms"] > 0
    assert backend[0]["shapes"] == {"image": [1, 6, 11, 3]}


def test_compile_counter_tallies_backend_compiles():
    """graftprof's per-bench-row compile accounting: the counter sees
    the real XLA compiles in its window (no EventLog needed) and stops
    counting once the window closes."""
    import jax

    with compile_track.count() as cc:
        # tiny unique kernel — below the persistent-cache threshold, so
        # it backend-compiles every run
        jax.jit(lambda x: x * 1.618 + 0.577)(np.ones((3, 5), np.float32))
    assert cc.n >= 1 and cc.seconds > 0
    n_before = cc.n
    jax.jit(lambda x: x * 2.718 - 1.414)(np.ones((3, 5), np.float32))
    assert cc.n == n_before  # closed window: no further tallies


# ---------------------------------------------------------------------------
# report folding
# ---------------------------------------------------------------------------

def _synthetic_events():
    mk = lambda t, **kw: dict(  # noqa: E731 — local record factory
        {"type": t, "t_wall": 0.0, "t_mono": 0.0, "process": 0, "step": 0},
        **kw)
    return [
        mk("run_meta", config_digest="abc", network="resnet50",
           batch_size=2, steps_per_epoch=4),
        mk("compile", phase="backend_compile", duration_ms=500.0,
           shapes=None),
        # graftprof: per-bucket XLA cost accounting — flops chosen so the
        # p50-20ms bucket lands at MFU 0.5 against the stamped peak
        mk("cost", label="train_step", shapes={"image": [2, 8, 8, 3]},
           peak_flops=1e12, flops=1e10, bytes_accessed=5e9,
           hbm_bytes=2e9, hbm_args=1.5e9, hbm_temps=4e8, hbm_output=1e8,
           hbm_alias=0.0),
        mk("step", step=1, epoch=0, batch=0, data_wait_ms=5.0,
           step_ms=20.0, canvas=[8, 8], pad_waste=0.25),
        mk("step", step=2, epoch=0, batch=1, data_wait_ms=1.0,
           step_ms=10.0, canvas=[8, 8], pad_waste=0.15),
        mk("step", step=2, epoch=0, batch=1, samples_per_sec=150.0,
           window=2),
        mk("compile", phase="backend_compile", duration_ms=300.0, step=2,
           shapes={"image": [1, 8, 8, 3]}),
        mk("compile", phase="jaxpr_trace", duration_ms=10.0, step=2),
        mk("step", step=3, epoch=0, batch=2, data_wait_ms=2.0,
           step_ms=10.0, canvas=[8, 8], pad_waste=0.25),
        mk("step", step=4, epoch=0, batch=3, data_wait_ms=2.0,
           step_ms=40.0, canvas=[8, 8], pad_waste=0.35),
        mk("trace", dir="obs/trace/step2", reason="step 2",
           summary={"phases": {"forward": 9.0, "host": 1.0},
                    "total_ms": 10.0, "events": 4, "top_ops": []}),
        mk("epoch", epoch=0, metrics={"TotalLoss": 1.0}, pad_waste=0.25),
        mk("checkpoint", epoch=1, prefix="p"),
        mk("eval", images=8, results={"mAP": 0.5}),
        mk("stall", waited_s=9.0),
        mk("crash", step=4, error="RuntimeError('boom')"),
    ]


def test_report_aggregates_synthetic_log():
    s = report.summarize(_synthetic_events())
    assert s["steps"] == 4 and s["epochs"] == 1 and s["checkpoints"] == 1
    # measured Speedometer window preferred over derived throughput
    assert s["throughput"]["img_s"] == 150.0
    assert s["throughput"]["step_ms_p50"] == 20.0
    assert s["throughput"]["step_ms_max"] == 40.0
    assert s["data_wait"]["fraction"] == pytest.approx(10.0 / 80.0)
    # only backend_compile counts as a compile; the one at step>=1 is a
    # steady-state recompile and surfaces its shape signature
    assert s["compile"]["count"] == 2
    assert s["compile"]["total_ms"] == 800.0
    assert s["compile"]["steady_state_count"] == 1
    assert s["compile"]["steady_state_shapes"] == [{"image": [1, 8, 8, 3]}]
    assert s["evals"] == [{"mAP": 0.5}]
    assert s["stalls"] == 1
    assert s["crash"]["step"] == 4
    # graftprof folds: the cost bucket joins the canvas-matched steps
    # (p50 20 ms at 1e10 flops against the stamped 1e12 peak → MFU 0.5)
    assert len(s["cost"]["buckets"]) == 1
    bucket = s["cost"]["buckets"][0]
    assert bucket["canvas"] == [8, 8] and bucket["steps"] == 4
    assert bucket["mfu"] == pytest.approx(0.5)
    assert s["cost"]["mfu"] == pytest.approx(0.5)
    assert s["cost"]["hbm_bytes"] == 2e9
    assert s["pad_waste"] == pytest.approx(0.25)  # p50 of the step events
    assert s["traces"][0]["reason"] == "step 2"
    assert s["traces"][0]["summary"]["phases"]["forward"] == 9.0
    blob = report.bench_blob(s)
    assert blob["value"] == 150.0 and blob["compile_count"] == 2
    assert blob["stall_count"] == 1
    assert blob["data_wait_fraction"] == pytest.approx(0.125)
    assert blob["mfu"] == pytest.approx(0.5)
    assert blob["hbm_bytes"] == 2e9
    assert blob["pad_waste"] == pytest.approx(0.25)
    assert "mfu 0.5" in report.render(s)
    # derived-throughput fallback when no Speedometer window exists
    s2 = report.summarize([e for e in _synthetic_events()
                           if "samples_per_sec" not in e])
    assert s2["throughput"]["img_s"] == pytest.approx(2 * 1000.0 / 20.0)


def test_report_cli_roundtrip(tmp_path):
    log = open_event_log(str(tmp_path / "run"))
    log.emit("run_meta", batch_size=1)
    log.emit("step", step_ms=10.0, data_wait_ms=1.0)
    log.close()
    out = tmp_path / "blob.json"
    assert report.main([str(tmp_path / "run"), "--json", str(out)]) == 0
    blob = json.loads(out.read_text())
    assert blob["steps"] == 1 and "compile_count" in blob
    # truncated tail line (killed run) is skipped, not fatal
    with open(log.path, "a") as fh:
        fh.write('{"type": "st')
    assert len(report.load_events(str(tmp_path / "run"))) == 2


# ---------------------------------------------------------------------------
# fit_detector integration (tier-1 acceptance gate)
# ---------------------------------------------------------------------------

OBS_TINY = {
    "image.pad_shape": (128, 128),
    "image.scales": ((128, 128),),
    "network.norm": "group",
    "network.freeze_at": 0,
    "network.anchor_scales": (2, 4, 8),
    "train.rpn_pre_nms_top_n": 256,
    "train.rpn_post_nms_top_n": 64,
    "train.batch_rois": 32,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "train.flip": False,
}


def _tiny_fit(tmp_path, prefix_name, **obs_overrides):
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = generate_config("resnet50", "synthetic",
                          **{**OBS_TINY, **obs_overrides})
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    return fit_detector(cfg, ds.gt_roidb(),
                        prefix=str(tmp_path / prefix_name),
                        end_epoch=1, frequent=2)


@pytest.mark.compile_heavy
def test_fit_detector_obs_enabled_and_report(tmp_path):
    """The acceptance gate: a short synthetic fit with obs enabled writes
    a run_meta + per-step + epoch event stream — including graftprof's
    cost/trace/pad-waste layer — and the report CLI folds it into
    throughput, compile-count, MFU and HBM fields."""
    obs_dir = tmp_path / "obsrun"
    params = _tiny_fit(tmp_path, "ckpt",
                       **{"obs.enabled": True, "obs.dir": str(obs_dir),
                          "obs.trace_at_step": 2, "obs.trace_steps": 1,
                          "obs.health_every": 2})
    assert params is not None
    events = report.load_events(str(obs_dir))
    types = {e["type"] for e in events}
    assert {"run_meta", "step", "epoch", "checkpoint", "cost",
            "trace", "health"} <= types

    # graftpulse rides the same fit: a health reading every 2nd dispatch
    # (4 dispatches -> 2), clean — all-zero nonfinite counts, finite
    # norms, no anomaly
    health = [e for e in events if e["type"] == "health"]
    assert [e["dispatch"] for e in health] == [2, 4]
    for e in health:
        assert all(v == 0 for v in e["nonfinite"].values())
        assert e["grad_norm"] > 0
    assert not [e for e in events if e["type"] == "anomaly"]

    # graftprof: one cost event for the single shape bucket, with real
    # XLA numbers behind the computed MFU
    cost = next(e for e in events if e["type"] == "cost")
    assert cost["flops"] > 0 and cost["hbm_bytes"] > 0
    assert cost["peak_flops"] > 0
    assert cost["shapes"]["image"] == [1, 128, 128, 3]
    # the armed window closed and folded (128px images on a 128 canvas:
    # pad_waste is an exact 0)
    trace = next(e for e in events if e["type"] == "trace")
    assert trace["reason"] == "step 2"
    assert trace["summary"] is None or trace["summary"]["events"] > 0

    meta = next(e for e in events if e["type"] == "run_meta")
    assert meta["batch_size"] == 1 and meta["steps_per_epoch"] == 4
    assert meta["mesh"] == {"data": 1, "model": 1}
    assert len(meta["config_digest"]) == 16

    timed = [e for e in events if e["type"] == "step" and "step_ms" in e]
    assert len(timed) == 4
    for e in timed:
        assert e["data_wait_ms"] >= 0 and e["step_ms"] > 0
        assert "dispatch_ms" in e
        assert e["canvas"] == [128, 128]
        assert e["pad_waste"] == 0.0  # 128px content on a 128 canvas
    epochs = [e for e in events if e["type"] == "epoch"]
    assert epochs[0]["epoch"] == 0
    assert "TotalLoss" in epochs[0]["metrics"]
    assert epochs[0]["pad_waste"] == 0.0  # the loader's counters
    assert epochs[0]["pad_canvas_px"] == 4 * 128 * 128

    # the report CLI (the artifact future BENCH/regression gates consume)
    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "-m", "mx_rcnn_tpu.obs.report", str(obs_dir),
         "--json", str(out)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "throughput" in proc.stdout
    blob = json.loads(out.read_text())
    assert blob["steps"] == 4
    assert blob["value"] > 0  # throughput (img/s) from the run
    assert isinstance(blob["compile_count"], int)
    assert blob["detail"]["epochs"] == 1
    assert blob["detail"]["checkpoints"] == 1
    assert blob["stall_count"] == 0
    # graftpulse + env-fingerprint fields ride the bench blob into the
    # perf ledger (anomaly accounting, environment-drift attribution)
    assert blob["anomaly_count"] == 0 and blob["health_checks"] == 2
    assert blob["detail"]["health"]["last"]["grad_norm"] > 0
    assert blob["jax_version"] and blob["jaxlib_version"]
    assert isinstance(blob["git_dirty"], bool)
    # graftprof: the folded blob carries the computed-cost fields the
    # perf ledger gates (MFU rounds to 0.0 at CPU step times — present,
    # not None, is the contract here)
    assert blob["mfu"] is not None
    assert blob["hbm_bytes"] > 0
    assert blob["pad_waste"] == 0.0
    assert blob["detail"]["cost"]["buckets"][0]["canvas"] == [128, 128]


@pytest.mark.compile_heavy
def test_fit_detector_obs_disabled_writes_nothing(tmp_path):
    """Default config: no obs directory, no JSONL — the telemetry layer
    must be invisible when off."""
    params = _tiny_fit(tmp_path, "ckpt2")
    assert params is not None
    assert not (tmp_path / "ckpt2.obs").exists()
    assert not any(p.name.endswith(".jsonl") for p in tmp_path.rglob("*"))
