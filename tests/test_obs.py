"""graftscope (mx_rcnn_tpu/obs) gates.

Unit layer: JSONL schema round-trip, StepTimer phase splits on a fake
loader, watchdog stall detection, report aggregation over a synthetic
event log, and the disabled sink's zero-event / zero-drain contract.

Integration layer (tier-1, compile_heavy): a short synthetic
``fit_detector`` run with obs enabled must produce a foldable event
stream — run_meta, per-step timing, epoch, checkpoint — and
``python -m mx_rcnn_tpu.obs.report`` must fold it into throughput +
compile-count fields; with obs disabled no file is written and the
MetricBag lazy-drain discipline is untouched.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs import (
    EVENT_TYPES,
    EventLog,
    NullEventLog,
    StallWatchdog,
    StepTimer,
    compile_track,
    event_log_path,
    obs_from_config,
    open_event_log,
    run_meta_fields,
)
from mx_rcnn_tpu.obs import report
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.metrics import MetricBag

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# EventLog
# ---------------------------------------------------------------------------

def test_event_log_schema_roundtrip(tmp_path):
    """One record of every type survives the JSONL round trip with the
    common stamps (wall/monotonic time, process, step) and its payload —
    including numpy scalars/arrays, which must land as plain JSON."""
    log = open_event_log(str(tmp_path), process_index=0)
    for i, t in enumerate(EVENT_TYPES):
        log.set_step(i)
        log.emit(t, payload=i, np_scalar=np.float32(1.5),  # graftlint: disable=obs-event-schema — iterating the schema itself
                 np_arr=np.arange(3))
    log.close()
    events = report.load_events(str(tmp_path))
    assert [e["type"] for e in events] == list(EVENT_TYPES)
    for i, e in enumerate(events):
        assert e["step"] == i and e["process"] == 0
        assert e["t_wall"] > 0 and e["t_mono"] > 0
        assert e["payload"] == i
        assert e["np_scalar"] == 1.5
        assert e["np_arr"] == [0, 1, 2]


def test_event_log_rejects_unknown_type(tmp_path):
    sink = EventLog(str(tmp_path / "e.jsonl"))
    with pytest.raises(ValueError, match="unknown event type"):
        sink.emit("not_a_type")
    sink.close()


def test_event_log_buffers_steps_flushes_critical(tmp_path):
    """step records buffer up to flush_every; stall/crash-class records
    hit disk immediately (they must survive the hang they diagnose)."""
    path = str(tmp_path / "e.jsonl")
    log = EventLog(path, flush_every=64)

    def lines():
        with open(path) as fh:
            return sum(1 for _ in fh)

    log.emit("step", step_ms=1.0)
    log.emit("step", step_ms=1.0)
    assert lines() == 0  # still buffered
    log.emit("stall", waited_s=9.0)
    assert lines() == 3  # critical record flushed the buffer with it
    log.close()
    assert lines() == 3


def test_event_log_path_per_process(tmp_path):
    assert event_log_path(str(tmp_path)).endswith("events.jsonl")
    assert event_log_path(str(tmp_path), 3).endswith("events.3.jsonl")


def test_run_meta_fields_digest_and_versions():
    cfg = generate_config("resnet50", "synthetic")
    fields = run_meta_fields(cfg, tool="test")
    assert len(fields["config_digest"]) == 16
    assert fields["network"] == "resnet50" and fields["tool"] == "test"
    assert "jax_version" in fields
    # digest tracks the config
    cfg2 = generate_config("resnet50", "synthetic",
                           **{"train.lr": 0.5})
    assert run_meta_fields(cfg2)["config_digest"] != fields["config_digest"]


def test_null_sink_is_inert(tmp_path):
    """The disabled sink touches nothing: no files, no state, and
    obs_from_config returns it without reading obs.dir."""
    n = NullEventLog()
    n.emit("step", step_ms=1.0)
    n.set_step(5)
    n.flush()
    n.close()
    assert n.step == 0 and n.path is None
    cfg = generate_config("resnet50", "synthetic",
                          **{"obs.dir": str(tmp_path / "never")})
    sink = obs_from_config(cfg)
    assert isinstance(sink, NullEventLog)
    assert not (tmp_path / "never").exists()
    assert os.listdir(tmp_path) == []


# ---------------------------------------------------------------------------
# StepTimer
# ---------------------------------------------------------------------------

def _slow_loader(n, wait_s):
    for i in range(n):
        time.sleep(wait_s)
        yield {"image": np.zeros((1, 4, 4, 3), np.float32), "i": i}


def test_step_timer_phase_split(tmp_path):
    """Each iteration over a fake loader emits a step event whose
    data_wait_ms reflects the loader's sleep, with dispatch_ms marked at
    the dispatched() call and step_ms covering the whole iteration."""
    log = open_event_log(str(tmp_path))
    timer = StepTimer(log)
    seen = []
    for i, batch in timer.iterate(0, _slow_loader(3, wait_s=0.02)):
        seen.append((i, batch["i"]))
        time.sleep(0.01)
        timer.dispatched()
    log.close()
    assert seen == [(0, 0), (1, 1), (2, 2)]
    steps = [e for e in report.load_events(str(tmp_path))
             if e["type"] == "step"]
    assert len(steps) == 3
    for n, e in enumerate(steps):
        assert e["step"] == n + 1  # global counter advanced per iteration
        assert e["epoch"] == 0 and e["batch"] == n
        assert e["data_wait_ms"] >= 15.0  # the 20 ms loader sleep
        assert e["dispatch_ms"] >= 8.0  # the 10 ms "dispatch"
        assert e["step_ms"] >= e["data_wait_ms"] + e["dispatch_ms"] - 1.0
    assert timer.total_steps == 3


def test_step_timer_disabled_is_passthrough_and_lazy():
    """With the null sink, iterate degrades to enumerate (same objects,
    zero events) and never drains a MetricBag — the lazy-drain
    discipline (train/metrics.py) is untouched, i.e. no per-step host
    sync is added by instrumentation."""
    timer = StepTimer(NullEventLog())
    batches = [{"x": 1}, {"x": 2}]
    bag = MetricBag()
    out = []
    for i, batch in timer.iterate(0, batches):
        bag.update({"TotalLoss": 1.0})
        timer.dispatched()
        out.append((i, batch))
    assert out == [(0, batches[0]), (1, batches[1])]
    assert out[0][1] is batches[0]  # identity: no copies, no wrapping
    assert len(bag._pending) == 2  # nothing forced a drain
    assert timer.total_steps == 0


# ---------------------------------------------------------------------------
# Speedometer emission
# ---------------------------------------------------------------------------

def test_speedometer_logs_and_emits(tmp_path):
    log = open_event_log(str(tmp_path))
    meter = Speedometer(batch_size=2, frequent=2, event_log=log)
    bag = MetricBag()
    bag.update({"TotalLoss": 1.0})
    assert meter(0, 0, bag) is None
    speed = meter(0, 1, bag)
    assert speed is not None and speed > 0
    log.close()
    windows = [e for e in report.load_events(str(tmp_path))
               if e["type"] == "step" and "samples_per_sec" in e]
    assert len(windows) == 1
    assert windows[0]["window"] == 2
    assert windows[0]["samples_per_sec"] == pytest.approx(speed, rel=1e-3)


# ---------------------------------------------------------------------------
# StallWatchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_on_stall_with_stacks(tmp_path):
    """An artificially stalled step trips the watchdog exactly once per
    episode, and the stall event carries this (main) thread's stack."""
    log = open_event_log(str(tmp_path))
    wd = StallWatchdog(log, stall_factor=2.0, min_stall_s=0.05, poll_s=10)
    for _ in range(5):
        wd.beat(0.01)
    assert wd.threshold_s() == pytest.approx(0.05)  # min_stall_s floor
    now = time.monotonic()
    assert not wd.check(now)  # fresh heartbeat: no stall
    assert wd.check(now + 1.0)  # stalled
    assert not wd.check(now + 2.0)  # one event per episode
    wd.beat(0.01)  # heartbeat re-arms the tripwire
    assert wd.check(time.monotonic() + 1.0)
    log.close()
    stalls = [e for e in report.load_events(str(tmp_path))
              if e["type"] == "stall"]
    assert len(stalls) == 2
    assert stalls[0]["waited_s"] >= 0.9
    assert stalls[0]["median_step_s"] == pytest.approx(0.01)
    assert any("test_obs" in stack or "MainThread" in name
               for name, stack in stalls[0]["stacks"].items())


def test_watchdog_threshold_scales_with_median():
    wd = StallWatchdog(NullEventLog(), stall_factor=10.0, min_stall_s=1.0)
    # pre-first-step: cold-start grace (compiles are slow, not stalls)
    assert wd.threshold_s() == pytest.approx(
        StallWatchdog.COLD_GRACE * 1.0)
    for d in (0.2, 0.3, 0.4):
        wd.beat(d)
    assert wd.threshold_s() == pytest.approx(3.0)  # 10 x median(0.3)


def test_watchdog_thread_emits(tmp_path):
    """The real daemon thread path: a stalled 'run' produces a stall
    event on disk without any synchronous check() calls."""
    log = open_event_log(str(tmp_path))
    wd = StallWatchdog(log, stall_factor=2.0, min_stall_s=0.05,
                       poll_s=0.02)
    wd.beat(0.01)  # one completed step arms the steady-state threshold
    wd.start()
    try:
        time.sleep(0.3)  # no further beats: stalled from here on
    finally:
        wd.stop()
    log.close()
    assert any(e["type"] == "stall"
               for e in report.load_events(str(tmp_path)))


# ---------------------------------------------------------------------------
# Compile tracking
# ---------------------------------------------------------------------------

def test_compile_tracker_emits_with_shape_signature(tmp_path):
    import jax

    log = open_event_log(str(tmp_path))
    assert compile_track.activate(log)
    try:
        compile_track.note_batch(
            {"image": np.zeros((1, 6, 11, 3), np.float32)})
        jax.jit(lambda x: x * 2.5 + 1.25)(np.ones((2, 3), np.float32))
    finally:
        compile_track.deactivate()
    log.close()
    compiles = [e for e in report.load_events(str(tmp_path))
                if e["type"] == "compile"]
    backend = [e for e in compiles if e["phase"] == "backend_compile"]
    assert backend, compiles  # tiny kernels are below the persistent-
    # cache threshold, so the jit above really XLA-compiles every run
    assert backend[0]["duration_ms"] > 0
    assert backend[0]["shapes"] == {"image": [1, 6, 11, 3]}


# ---------------------------------------------------------------------------
# report folding
# ---------------------------------------------------------------------------

def _synthetic_events():
    mk = lambda t, **kw: dict(  # noqa: E731 — local record factory
        {"type": t, "t_wall": 0.0, "t_mono": 0.0, "process": 0, "step": 0},
        **kw)
    return [
        mk("run_meta", config_digest="abc", network="resnet50",
           batch_size=2, steps_per_epoch=4),
        mk("compile", phase="backend_compile", duration_ms=500.0,
           shapes=None),
        mk("step", step=1, epoch=0, batch=0, data_wait_ms=5.0,
           step_ms=20.0),
        mk("step", step=2, epoch=0, batch=1, data_wait_ms=1.0,
           step_ms=10.0),
        mk("step", step=2, epoch=0, batch=1, samples_per_sec=150.0,
           window=2),
        mk("compile", phase="backend_compile", duration_ms=300.0, step=2,
           shapes={"image": [1, 8, 8, 3]}),
        mk("compile", phase="jaxpr_trace", duration_ms=10.0, step=2),
        mk("step", step=3, epoch=0, batch=2, data_wait_ms=2.0,
           step_ms=10.0),
        mk("step", step=4, epoch=0, batch=3, data_wait_ms=2.0,
           step_ms=40.0),
        mk("epoch", epoch=0, metrics={"TotalLoss": 1.0}),
        mk("checkpoint", epoch=1, prefix="p"),
        mk("eval", images=8, results={"mAP": 0.5}),
        mk("stall", waited_s=9.0),
        mk("crash", step=4, error="RuntimeError('boom')"),
    ]


def test_report_aggregates_synthetic_log():
    s = report.summarize(_synthetic_events())
    assert s["steps"] == 4 and s["epochs"] == 1 and s["checkpoints"] == 1
    # measured Speedometer window preferred over derived throughput
    assert s["throughput"]["img_s"] == 150.0
    assert s["throughput"]["step_ms_p50"] == 20.0
    assert s["throughput"]["step_ms_max"] == 40.0
    assert s["data_wait"]["fraction"] == pytest.approx(10.0 / 80.0)
    # only backend_compile counts as a compile; the one at step>=1 is a
    # steady-state recompile and surfaces its shape signature
    assert s["compile"]["count"] == 2
    assert s["compile"]["total_ms"] == 800.0
    assert s["compile"]["steady_state_count"] == 1
    assert s["compile"]["steady_state_shapes"] == [{"image": [1, 8, 8, 3]}]
    assert s["evals"] == [{"mAP": 0.5}]
    assert s["stalls"] == 1
    assert s["crash"]["step"] == 4
    blob = report.bench_blob(s)
    assert blob["value"] == 150.0 and blob["compile_count"] == 2
    assert blob["stall_count"] == 1
    assert blob["data_wait_fraction"] == pytest.approx(0.125)
    # derived-throughput fallback when no Speedometer window exists
    s2 = report.summarize([e for e in _synthetic_events()
                           if "samples_per_sec" not in e])
    assert s2["throughput"]["img_s"] == pytest.approx(2 * 1000.0 / 20.0)


def test_report_cli_roundtrip(tmp_path):
    log = open_event_log(str(tmp_path / "run"))
    log.emit("run_meta", batch_size=1)
    log.emit("step", step_ms=10.0, data_wait_ms=1.0)
    log.close()
    out = tmp_path / "blob.json"
    assert report.main([str(tmp_path / "run"), "--json", str(out)]) == 0
    blob = json.loads(out.read_text())
    assert blob["steps"] == 1 and "compile_count" in blob
    # truncated tail line (killed run) is skipped, not fatal
    with open(log.path, "a") as fh:
        fh.write('{"type": "st')
    assert len(report.load_events(str(tmp_path / "run"))) == 2


# ---------------------------------------------------------------------------
# fit_detector integration (tier-1 acceptance gate)
# ---------------------------------------------------------------------------

OBS_TINY = {
    "image.pad_shape": (128, 128),
    "image.scales": ((128, 128),),
    "network.norm": "group",
    "network.freeze_at": 0,
    "network.anchor_scales": (2, 4, 8),
    "train.rpn_pre_nms_top_n": 256,
    "train.rpn_post_nms_top_n": 64,
    "train.batch_rois": 32,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "train.flip": False,
}


def _tiny_fit(tmp_path, prefix_name, **obs_overrides):
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = generate_config("resnet50", "synthetic",
                          **{**OBS_TINY, **obs_overrides})
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    return fit_detector(cfg, ds.gt_roidb(),
                        prefix=str(tmp_path / prefix_name),
                        end_epoch=1, frequent=2)


@pytest.mark.compile_heavy
def test_fit_detector_obs_enabled_and_report(tmp_path):
    """The acceptance gate: a short synthetic fit with obs enabled writes
    a run_meta + per-step + epoch event stream, and the report CLI folds
    it into throughput and compile-count fields."""
    obs_dir = tmp_path / "obsrun"
    params = _tiny_fit(tmp_path, "ckpt",
                       **{"obs.enabled": True, "obs.dir": str(obs_dir)})
    assert params is not None
    events = report.load_events(str(obs_dir))
    types = {e["type"] for e in events}
    assert {"run_meta", "step", "epoch", "checkpoint"} <= types

    meta = next(e for e in events if e["type"] == "run_meta")
    assert meta["batch_size"] == 1 and meta["steps_per_epoch"] == 4
    assert meta["mesh"] == {"data": 1, "model": 1}
    assert len(meta["config_digest"]) == 16

    timed = [e for e in events if e["type"] == "step" and "step_ms" in e]
    assert len(timed) == 4
    for e in timed:
        assert e["data_wait_ms"] >= 0 and e["step_ms"] > 0
        assert "dispatch_ms" in e
    epochs = [e for e in events if e["type"] == "epoch"]
    assert epochs[0]["epoch"] == 0
    assert "TotalLoss" in epochs[0]["metrics"]

    # the report CLI (the artifact future BENCH/regression gates consume)
    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "-m", "mx_rcnn_tpu.obs.report", str(obs_dir),
         "--json", str(out)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "throughput" in proc.stdout
    blob = json.loads(out.read_text())
    assert blob["steps"] == 4
    assert blob["value"] > 0  # throughput (img/s) from the run
    assert isinstance(blob["compile_count"], int)
    assert blob["detail"]["epochs"] == 1
    assert blob["detail"]["checkpoints"] == 1
    assert blob["stall_count"] == 0


@pytest.mark.compile_heavy
def test_fit_detector_obs_disabled_writes_nothing(tmp_path):
    """Default config: no obs directory, no JSONL — the telemetry layer
    must be invisible when off."""
    params = _tiny_fit(tmp_path, "ckpt2")
    assert params is not None
    assert not (tmp_path / "ckpt2.obs").exists()
    assert not any(p.name.endswith(".jsonl") for p in tmp_path.rglob("*"))
