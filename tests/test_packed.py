"""Packed pre-decoded shard format (data/packed.py).

The packed path must be a drop-in for the JPEG path: same batches through
the same AnchorLoader API, numerics equal up to uint8 re-quantization at
pack time.
"""

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.loader import AnchorLoader, _load_roidb_entry
from mx_rcnn_tpu.data.packed import (
    load_packed_roidb,
    write_packed_dataset,
)

cv2 = pytest.importorskip("cv2")


def _cfg(**over):
    base = {
        "image.scales": ((128, 214),),
        "image.pad_shape": (136, 216),
        "train.batch_images": 1,
        "train.flip": False,
        "train.max_gt_boxes": 4,
    }
    base.update(over)
    return generate_config("resnet50", "synthetic", **base)


def _jpeg_roidb(tmp_path, n=6, seed=0):
    rs = np.random.RandomState(seed)
    roidb = []
    for i in range(n):
        h, w = (120, 160) if i % 3 else (160, 120)  # mixed orientation
        img = (rs.rand(h // 4, w // 4, 3) * 255).astype(np.uint8)
        img = cv2.resize(img, (w, h), interpolation=cv2.INTER_CUBIC)
        path = str(tmp_path / f"{i:03d}.jpg")
        cv2.imwrite(path, img, [cv2.IMWRITE_JPEG_QUALITY, 95])
        roidb.append({
            "image": path, "height": h, "width": w,
            # x1 = 5+i: makes every record identifiable after the pack's
            # orientation regrouping (the tests match records by boxes).
            "boxes": np.asarray([[5 + i, 5, 60 + i, 50]], np.float32),
            "gt_classes": np.asarray([1], np.int32),
            "flipped": False,
        })
    return roidb


def test_packed_matches_jpeg_path(tmp_path):
    cfg = _cfg()
    roidb = _jpeg_roidb(tmp_path)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"), shard_images=2)
    packed = load_packed_roidb(str(tmp_path / "pack"))
    assert len(packed) == len(roidb)
    # Manifest preserves per-image identity through the orientation
    # regrouping: match records by original size + boxes.
    by_hw = {(r["height"], r["width"], float(r["boxes"][0, 0])): r
             for r in packed}
    for entry in roidb:
        key = (entry["height"], entry["width"],
               float(entry["boxes"][0, 0]))
        p = by_hw[key]
        # Square cover: holds both orientations for the direct comparison
        # (batch paths orient the bucket via resolve_pad_bucket).
        sq = (216, 216)
        img_j, info_j, boxes_j, cls_j = _load_roidb_entry(entry, cfg,
                                                          pad=sq)
        img_p, info_p, boxes_p, cls_p = _load_roidb_entry(p, cfg, pad=sq)
        assert img_j.shape == img_p.shape
        np.testing.assert_allclose(info_j, info_p, rtol=1e-6)
        np.testing.assert_allclose(boxes_j, boxes_p, rtol=1e-5)
        np.testing.assert_array_equal(cls_j, cls_p)
        # uint8 re-quantization at pack time: <= 0.5 pixel-value LSB,
        # i.e. <= 0.5/std after normalization.
        diff = np.abs(img_j - img_p).max()
        assert diff <= 0.6 / min(cfg.image.pixel_stds), diff


def test_packed_flip_matches_jpeg_flip(tmp_path):
    cfg = _cfg()
    roidb = _jpeg_roidb(tmp_path, n=2)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
    packed = load_packed_roidb(str(tmp_path / "pack"))
    by_id = {float(r["boxes"][0, 0]): r for r in packed}
    for entry in roidb:
        p = dict(by_id[float(entry["boxes"][0, 0])])
        e = dict(entry)
        e["flipped"] = p["flipped"] = True
        sq = (216, 216)
        img_j, _, boxes_j, _ = _load_roidb_entry(e, cfg, pad=sq)
        img_p, _, boxes_p, _ = _load_roidb_entry(p, cfg, pad=sq)
        np.testing.assert_allclose(boxes_j, boxes_p, rtol=1e-5)
        # Content mirrored the same way (resize<->mirror commute up to
        # interpolation detail at the right edge).
        diff = np.abs(img_j - img_p).mean()
        assert diff < 0.2, diff


def test_packed_through_anchor_loader(tmp_path):
    cfg = _cfg(**{"train.batch_images": 2})
    roidb = _jpeg_roidb(tmp_path)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
    packed = load_packed_roidb(str(tmp_path / "pack"))
    loader = AnchorLoader(packed, cfg, num_shards=1, seed=0)
    batches = list(loader)
    assert len(batches) == len(packed) // 2
    for b in batches:
        assert b["image"].dtype == np.float32
        assert np.isfinite(b["image"]).all()
        assert b["gt_valid"].any()


def test_packed_multiscale_through_anchor_loader(tmp_path):
    """A multi-scale config packs one shard set per scale; the loader's
    per-batch scale draw reads the matching set (the FPN acceptance
    recipe trains multi-scale)."""
    cfg = _cfg(**{
        "image.scales": ((96, 160), (128, 214)),
        "image.pad_shapes": ((104, 168), (136, 216)),
        "image.pad_shape": (216, 216),
        "train.batch_images": 2,
    })
    roidb = _jpeg_roidb(tmp_path, n=8)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
    packed = load_packed_roidb(str(tmp_path / "pack"))
    assert all(sorted(r["packed"]) == [0, 1] for r in packed)
    shapes = set()
    for _ in range(4):  # several epochs: both scales get drawn
        for b in AnchorLoader(packed, cfg, num_shards=1, seed=0):
            shapes.add(b["image"].shape[1:3])
            assert np.isfinite(b["image"]).all()
    assert len(shapes) >= 2, shapes


def test_packed_scale_mismatch_raises(tmp_path):
    cfg = _cfg()
    roidb = _jpeg_roidb(tmp_path, n=2)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
    packed = load_packed_roidb(str(tmp_path / "pack"))
    with pytest.raises(ValueError, match="scale_idx"):
        _load_roidb_entry(packed[0], cfg, scale_idx=1)


def test_packed_geometry_validation(tmp_path):
    """Loading with a config whose image geometry differs from pack time
    must fail loudly (silent wrong-resolution training otherwise)."""
    cfg = _cfg()
    roidb = _jpeg_roidb(tmp_path, n=2)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
    assert len(load_packed_roidb(str(tmp_path / "pack"), cfg)) == 2
    other = _cfg(**{"image.scales": ((96, 160),)})
    with pytest.raises(ValueError, match="geometry"):
        load_packed_roidb(str(tmp_path / "pack"), other)


def test_packed_partial_scale_coverage_rejected(tmp_path):
    """A pack restricted to a subset of the config's scales must fail at
    LOAD time, not mid-epoch when the missing scale is drawn."""
    cfg = _cfg(**{
        "image.scales": ((96, 160), (128, 214)),
        "image.pad_shapes": ((104, 168), (136, 216)),
        "image.pad_shape": (216, 216),
    })
    roidb = _jpeg_roidb(tmp_path, n=2)
    write_packed_dataset(roidb, cfg, str(tmp_path / "pack"), scale_idx=0)
    with pytest.raises(ValueError, match="missing"):
        load_packed_roidb(str(tmp_path / "pack"), cfg)


def test_packed_old_format_rejected(tmp_path):
    import pickle

    (tmp_path / "pack").mkdir()
    with open(tmp_path / "pack" / "manifest.pkl", "wb") as f:
        pickle.dump([{"packed_file": "x.npy"}], f)  # pre-multi-scale list
    with pytest.raises(ValueError, match="re-pack"):
        load_packed_roidb(str(tmp_path / "pack"))


def test_packed_rejects_flipped_input(tmp_path):
    cfg = _cfg()
    roidb = _jpeg_roidb(tmp_path, n=1)
    roidb[0]["flipped"] = True
    with pytest.raises(ValueError, match="UNFLIPPED"):
        write_packed_dataset(roidb, cfg, str(tmp_path / "pack"))
