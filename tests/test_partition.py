"""Tensor parallelism (parallel/partition.py): spec rules, placement,
and TP-vs-replicated train-step parity.

The reference has no model parallelism (SURVEY.md §3.2); these tests pin
the TPU-native TP extension: Megatron-split weights over the mesh `model`
axis with GSPMD-inserted collectives, composed with DP on the `data` axis.
Run on the conftest 8-device CPU mesh; comparisons use float32 compute so
shard-order summation noise stays inside tight tolerances (the bf16
lesson from test_ulysses_attention_matches_dense).
"""

from functools import partial

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy
from jax.sharding import PartitionSpec as P

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.parallel.partition import (
    shard_params,
    shard_train_state,
    tp_param_specs,
)
from mx_rcnn_tpu.train.optimizer import build_optimizer
from mx_rcnn_tpu.train.step import create_train_state, make_train_step


def _vit_cfg(**overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 2,
        "network.vit_dim": 32,
        "network.vit_depth": 2,
        "network.vit_heads": 2,
        "network.vit_window": 4,
        "train.compute_dtype": "f32",
        "network.tensor_parallel": True,
        "train.fpn_rpn_pre_nms_per_level": 64,
        "train.rpn_post_nms_top_n": 64,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
    }
    base.update(overrides)
    return generate_config("vitdet_b", "synthetic", **base)


def _batch(rng, b=2, size=128):
    one = {
        "image": rng.randn(1, size, size, 3).astype(np.float32),
        "im_info": np.asarray([[size, size, 1.0]], np.float32),
        "gt_boxes": np.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": np.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": np.asarray([[True, True] + [False] * 6]),
    }
    return {k: np.repeat(v, b, axis=0) for k, v in one.items()}


def _flat(tree):
    return {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def test_spec_rules_match_expected_leaves():
    cfg = _vit_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    specs = _flat(tp_param_specs(params))
    assert specs["params/features/block0/attn/qkv/kernel"] == P(None, "model")
    assert specs["params/features/block0/attn/proj/kernel"] == P("model", None)
    assert specs["params/features/block0/mlp1/kernel"] == P(None, "model")
    assert specs["params/features/block0/mlp2/kernel"] == P("model", None)
    # The paired FC box head is split; everything conv-ish is replicated.
    assert specs["params/head/fc6/kernel"] == P(None, "model")
    assert specs["params/head/fc7/kernel"] == P("model", None)
    assert specs["params/features/patch_embed/kernel"] == P()
    assert specs["params/cls_score/kernel"] == P()


def test_shard_params_places_on_model_axis():
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _vit_cfg()
    mesh = create_mesh("2x2")
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    sharded, shardings = shard_params(params, mesh)
    flat = _flat(sharded)
    qkv = flat["params/features/block0/attn/qkv/kernel"]
    assert not qkv.sharding.is_fully_replicated
    # 32x96 kernel split on the 2-way model axis → 32x48 shards.
    assert qkv.addressable_shards[0].data.shape == (32, 48)
    assert flat["params/features/patch_embed/kernel"].sharding.is_fully_replicated
    # Values survive placement bit-exactly.
    np.testing.assert_array_equal(
        np.asarray(qkv), np.asarray(_flat(params)["params/features/block0/attn/qkv/kernel"]))


def test_indivisible_dims_fall_back_to_replicated():
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    # vit_dim 24, model axis 4: qkv out = 72 ≡ 0 mod 4 but mlp hidden
    # 96/4 ok; use heads=3/dim=24 with model=4 → 24*3=72/4=18 fine...
    # pick dims that do NOT divide: dim 20 → qkv 60, 60 % 8.
    cfg = _vit_cfg(**{"network.vit_dim": 20, "network.vit_heads": 2})
    mesh = create_mesh("1x8")
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    sharded, _ = shard_params(params, mesh)
    flat = _flat(sharded)
    # 20x60 qkv: 60 % 8 != 0 → replicated, not padded.
    assert flat["params/features/block0/attn/qkv/kernel"].sharding.is_fully_replicated


def _run_steps(cfg, params, batch, mesh=None, tp=False, n_steps=2):
    model = zoo.build_model(cfg)
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    specs = None
    if tp:
        specs = tp_param_specs(state.params)
        state = shard_train_state(state, mesh, specs)
    step = make_train_step(model, cfg, mesh=mesh, donate=False,
                           forward_fn=zoo.forward_train, param_specs=specs)
    losses = []
    for i in range(n_steps):
        b = shard_batch(batch, mesh) if mesh is not None else batch
        state, metrics = step(state, b, jax.random.PRNGKey(7 + i))
        losses.append(float(metrics["TotalLoss"]))
    return losses, jax.device_get(state.params)


@pytest.mark.xfail(
    not hasattr(jax.lax, "pvary") and not hasattr(jax.lax, "pcast"),
    reason="pre-varying-type jax (< 0.5): the old GSPMD partitioner's "
           "bf16 reduction order drifts ~4e-4 on step 1 and AdamW "
           "amplifies it on step 2, exceeding the rtol calibrated on "
           "newer XLA (see the matching marker in test_pipeline.py)",
    strict=False)
def test_vitdet_tp_step_matches_replicated(rng):
    """DP×TP (2x2 mesh) reproduces the single-device step: same losses,
    same updated params — GSPMD collectives change only the schedule."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _vit_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = _batch(rng)

    ref_losses, ref_params = _run_steps(cfg, params, batch)
    mesh = create_mesh("2x2")
    tp_losses, tp_params = _run_steps(cfg, params, batch, mesh=mesh, tp=True)

    np.testing.assert_allclose(tp_losses, ref_losses, rtol=2e-4)
    ref_flat, tp_flat = _flat(ref_params), _flat(tp_params)
    for name in ("params/features/block0/mlp1/kernel",
                 "params/head/fc6/kernel",
                 "params/features/patch_embed/kernel"):
        np.testing.assert_allclose(tp_flat[name], ref_flat[name],
                                   rtol=1e-3, atol=1e-5, err_msg=name)


def _detr_tp_cfg(**overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 2,
        "network.detr_queries": 20,
        "network.detr_hidden": 64,
        "network.detr_heads": 4,
        "network.detr_enc_layers": 2,
        "network.detr_dec_layers": 2,
        "network.norm": "group",
        "network.freeze_at": 0,
        "train.compute_dtype": "f32",
        "network.tensor_parallel": True,
        "train.max_gt_boxes": 8,
    }
    base.update(overrides)
    return generate_config("detr_r50", "synthetic", **base)


def test_detr_tp_step_matches_replicated(rng):
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _detr_tp_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    specs = _flat(tp_param_specs(params))
    assert specs["params/enc0/self_attn/q/kernel"] == P(None, "model")
    assert specs["params/dec0/cross_attn/proj/kernel"] == P("model", None)
    # The FFN pair holds the largest DETR matrices — it MUST be split.
    assert specs["params/enc0/ffn1/kernel"] == P(None, "model")
    assert specs["params/enc0/ffn2/kernel"] == P("model", None)
    assert specs["params/dec0/ffn1/kernel"] == P(None, "model")
    batch = _batch(rng)

    ref_losses, _ = _run_steps(cfg, params, batch)
    mesh = create_mesh("2x2")
    tp_losses, _ = _run_steps(cfg, params, batch, mesh=mesh, tp=True)
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=5e-4)


def test_shard_train_state_keeps_opt_state_values(rng):
    """A restored (nonzero) opt_state survives TP placement bit-exactly —
    the resume path shards, never re-initializes."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _vit_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    # One plain step gives nonzero momentum slots.
    step = make_train_step(model, cfg, donate=False,
                           forward_fn=zoo.forward_train)
    state, _ = step(state, _batch(rng), jax.random.PRNGKey(3))
    before = jax.device_get(state.opt_state)

    mesh = create_mesh("2x2")
    sharded = shard_train_state(state, mesh)
    after = jax.device_get(sharded.opt_state)
    jax.tree.map(np.testing.assert_array_equal, before, after)
    assert int(sharded.step) == int(state.step)


def test_fpn_fc_head_tp_runs(rng):
    """The classic-family TP surface: TwoFCHead fc6/fc7 split under a
    2x2 mesh trains one finite step (conv trunk replicated)."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = generate_config(
        "resnet50_fpn", "synthetic",
        **{
            "image.pad_shape": (128, 128),
            "train.batch_images": 2,
            "train.compute_dtype": "f32",
            "network.tensor_parallel": True,
            "network.norm": "group",
            "network.freeze_at": 0,
            "train.fpn_rpn_pre_nms_per_level": 64,
            "train.rpn_post_nms_top_n": 64,
            "train.batch_rois": 32,
            "train.max_gt_boxes": 8,
        })
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    specs = _flat(tp_param_specs(params))
    assert specs["params/head/fc6/kernel"] == P(None, "model")
    mesh = create_mesh("2x2")
    losses, _ = _run_steps(cfg, params, _batch(rng), mesh=mesh, tp=True,
                           n_steps=1)
    assert np.isfinite(losses[0])


def test_fit_detector_tp_smoke(tmp_path, rng):
    """The full train loop (loader → TP shard → jitted step → checkpoint)
    with tensor_parallel on a 2x2 mesh — covers the fit_detector wiring,
    not just the bare step."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = _detr_tp_cfg(**{
        "image.scales": ((128, 128),),
        "train.compute_dtype": "bf16",  # the production dtype path
        "train.batch_images": 1,
        "train.flip": False,
        "train.lr_step": (100,),
    })
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    history = []
    fit_detector(cfg, ds.gt_roidb(), prefix=str(tmp_path / "tp"),
                 end_epoch=1, frequent=1000, seed=0, mesh_spec="2x2",
                 epoch_callback=lambda e, s, b: history.append(
                     b.get()["TotalLoss"]))
    assert len(history) == 1 and np.isfinite(history).all(), history
    assert (tmp_path / "tp" / "0001").exists()
