"""Pipeline parallelism (parallel/pipeline.py + the staged ViT backbone).

The GPipe schedule must be a pure re-ordering: pipelined forward AND
backward match the sequential stage composition exactly (float32). The
reference has no model parallelism (SURVEY.md §3.2) — this is TPU-native
surface like TP/SP.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.parallel.pipeline import pipeline_apply


def _toy(rng, s=4):
    w = jnp.asarray(rng.randn(s, 16, 16) * 0.3, jnp.float32)
    b = jnp.asarray(rng.randn(s, 16) * 0.1, jnp.float32)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def sequential(params, x):
        y = x
        for i in range(s):
            y = stage_fn(jax.tree.map(lambda a: a[i], params), y)
        return y

    return {"w": w, "b": b}, stage_fn, sequential


def test_toy_pipeline_matches_sequential(rng):
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, sequential = _toy(rng)
    x = jnp.asarray(rng.randn(8, 5, 16), jnp.float32)
    out = jax.jit(
        lambda p, x: pipeline_apply(stage_fn, p, x, mesh, "model"))(params, x)
    np.testing.assert_allclose(out, sequential(params, x), rtol=1e-6)


def test_toy_pipeline_gradients_match(rng):
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, sequential = _toy(rng)
    x = jnp.asarray(rng.randn(8, 5, 16), jnp.float32)

    g_pp = jax.jit(jax.grad(
        lambda p: jnp.sum(pipeline_apply(stage_fn, p, x, mesh, "model") ** 2)
    ))(params)
    g_seq = jax.jit(jax.grad(
        lambda p: jnp.sum(sequential(p, x) ** 2)))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        g_pp, g_seq)


def test_more_microbatches_shrink_nothing_numerically(rng):
    """m=8 over 4 stages (smaller bubble) is still exact."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, sequential = _toy(rng)
    x = jnp.asarray(rng.randn(16, 5, 16), jnp.float32)
    out = jax.jit(lambda p, x: pipeline_apply(
        stage_fn, p, x, mesh, "model", microbatches=8))(params, x)
    np.testing.assert_allclose(out, sequential(params, x), rtol=1e-6)


def test_microbatch_data_shard_mismatch_raises(rng):
    """Microbatch size must still divide over the data axis (DP x PP)."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, _ = _toy(rng)
    x = jnp.asarray(rng.randn(8, 5, 16), jnp.float32)
    with pytest.raises(ValueError, match="data axis"):
        pipeline_apply(stage_fn, params, x, mesh, "model", microbatches=8)


def test_stage_axis_mesh_mismatch_raises(rng):
    """S=8 stacked stages over a 4-way axis would silently compose only
    every other stage via shard_map slicing — must hard-error."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, _ = _toy(rng, s=8)
    x = jnp.asarray(rng.randn(8, 5, 16), jnp.float32)
    with pytest.raises(ValueError, match="stage_params leading axis"):
        pipeline_apply(stage_fn, params, x, mesh, "model")


def test_indivisible_microbatch_raises(rng):
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("2x4")
    params, stage_fn, _ = _toy(rng)
    x = jnp.asarray(rng.randn(6, 5, 16), jnp.float32)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_fn, params, x, mesh, "model")


def _vit_pp_cfg(pp_stages=2, **overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 4,
        "network.vit_dim": 32,
        "network.vit_depth": 4,
        "network.vit_heads": 2,
        "network.vit_window": 4,
        "train.compute_dtype": "f32",
        "network.pp_stages": pp_stages,
        "train.fpn_rpn_pre_nms_per_level": 64,
        "train.rpn_post_nms_top_n": 64,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
    }
    base.update(overrides)
    return generate_config("vitdet_b", "synthetic", **base)


def _batch(rng, b=4):
    one = {
        "image": rng.randn(1, 128, 128, 3).astype(np.float32),
        "im_info": np.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": np.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": np.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": np.asarray([[True, True] + [False] * 6]),
    }
    return {k: np.repeat(v, b, axis=0) for k, v in one.items()}


@pytest.mark.xfail(
    not hasattr(jax.lax, "pvary") and not hasattr(jax.lax, "pcast"),
    reason="pre-varying-type jax (< 0.5): the old partitioner's bf16 "
           "reduction order drifts ~8e-4 on step 1 and AdamW amplifies "
           "it on step 2, exceeding the rtol calibrated on newer XLA "
           "(the ring/ulysses parity tests still pass at tight rtol, so "
           "shard_map itself is numerically sound here)",
    strict=False)
def test_vitdet_pp_train_step_matches_sequential(rng):
    """Two DP x PP train steps reproduce the single-device staged run —
    the pipeline is a schedule, not a numerics change."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = _vit_pp_cfg()
    batch = _batch(rng)
    model_seq = zoo.build_model(cfg)  # no mesh: sequential staged backbone
    params = zoo.init_params(model_seq, cfg, jax.random.PRNGKey(0))

    def run(model, mesh):
        tx = build_optimizer(cfg, params, steps_per_epoch=10)
        state = create_train_state(params, tx)
        step = make_train_step(model, cfg, mesh=mesh, donate=False,
                               forward_fn=zoo.forward_train)
        losses = []
        for i in range(2):
            b = shard_batch(batch, mesh) if mesh is not None else batch
            state, metrics = step(state, b, jax.random.PRNGKey(7 + i))
            losses.append(float(metrics["TotalLoss"]))
        return losses

    ref = run(model_seq, None)
    mesh = create_mesh("2x2")
    pp = run(zoo.build_model(cfg, mesh=mesh), mesh)
    np.testing.assert_allclose(pp, ref, rtol=2e-4)


def test_pp_and_tp_are_mutually_exclusive():
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _vit_pp_cfg(**{"network.tensor_parallel": True})
    with pytest.raises(ValueError, match="model' axis"):
        zoo.build_model(cfg, mesh=create_mesh("2x2"))


def test_pp_and_sp_are_mutually_exclusive():
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = _vit_pp_cfg(**{"network.use_ring_attention": True})
    with pytest.raises(ValueError, match="model' axis"):
        zoo.build_model(cfg, mesh=create_mesh("2x2"))


def test_pp_mesh_size_mismatch_raises():
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    cfg = _vit_pp_cfg(pp_stages=4)
    with pytest.raises(ValueError, match="pp_stages"):
        zoo.build_model(cfg, mesh=create_mesh("4x2"))


def test_pp_depth_not_divisible_raises():
    cfg = _vit_pp_cfg(pp_stages=3)
    with pytest.raises(ValueError, match="divide"):
        zoo.build_model(cfg).init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 64, 64, 3), jnp.float32),
            jnp.asarray([[0.0, 0, 0, 31, 31]], jnp.float32))

def test_fit_detector_pp_smoke(tmp_path, rng):
    """The full train loop with the pipelined staged encoder on a 2x2
    mesh (DP x PP) — covers loader batch shapes, microbatch divisibility,
    and checkpointing of the stacked stage params."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = _vit_pp_cfg(**{
        "image.scales": ((128, 128),),
        "train.batch_images": 2,  # global 4 → 2 microbatches × 2 data shards
        "train.flip": False,
        "train.lr_step": (100,),
    })
    ds = SyntheticDataset("train", num_images=8, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    history = []
    fit_detector(cfg, ds.gt_roidb(), prefix=str(tmp_path / "pp"),
                 end_epoch=1, frequent=1000, seed=0, mesh_spec="2x2",
                 epoch_callback=lambda e, s, b: history.append(
                     b.get()["TotalLoss"]))
    assert len(history) == 1 and np.isfinite(history).all(), history
    assert (tmp_path / "pp" / "0001").exists()


@pytest.fixture(scope="module")
def seq_vit8():
    """Depth-8 sequential ViTDet (cfg, model, params) — shared by both
    stage-count parametrizations of the conversion gate (identical
    across them; the test never mutates the tree)."""
    cfg_seq = _vit_pp_cfg(pp_stages=0, **{"network.vit_depth": 8,
                                          "train.batch_images": 1})
    model_seq = zoo.build_model(cfg_seq)
    params_seq = zoo.init_params(model_seq, cfg_seq, jax.random.PRNGKey(0))
    return cfg_seq, model_seq, params_seq


@pytest.mark.parametrize("stages_n", [2, 4])
def test_sequential_to_staged_checkpoint_conversion(rng, seq_vit8, stages_n):
    """A sequentially-trained ViTDet param tree converts to the staged/PP
    layout with identical numerics (and back, bit-exact round trip) for
    EVERY supported stage count — the staged model preserves the
    sequential global-attention placement (depth 8: globals {1,3,5,7} →
    in-stage {1,3} per half at stages_n=2, {1} per quarter at 4)."""
    from mx_rcnn_tpu.models.vit import (
        sequential_to_staged, staged_to_sequential)

    cfg_seq, model_seq, params_seq = seq_vit8
    cfg_pp = _vit_pp_cfg(pp_stages=stages_n, **{"network.vit_depth": 8,
                                                "train.batch_images": 1})
    staged = sequential_to_staged(params_seq, stages_n)

    model_pp = zoo.build_model(cfg_pp)  # no mesh: sequential staged exec
    batch = _batch(rng, b=1)
    l_seq, _ = jax.jit(
        lambda p, b, r: zoo.forward_train(model_seq, p, b, r, cfg_seq)
    )(params_seq, batch, jax.random.PRNGKey(3))
    l_pp, _ = jax.jit(
        lambda p, b, r: zoo.forward_train(model_pp, p, b, r, cfg_pp)
    )(staged, batch, jax.random.PRNGKey(3))
    np.testing.assert_allclose(float(l_pp), float(l_seq), rtol=1e-6)

    # Bit-exact round trip.
    back = staged_to_sequential(staged)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 params_seq, back)


def test_sequential_to_staged_rejects_mismatched_layout(rng, seq_vit8):
    from mx_rcnn_tpu.models.vit import (
        sequential_to_staged, staged_to_sequential)

    _, _, params_seq = seq_vit8
    # 8 stages over depth 8 (per=1): sequential globals {1,3,5,7} give
    # alternating empty/global per-stage patterns — not preservable.
    with pytest.raises(ValueError, match="preserve"):
        sequential_to_staged(params_seq, 8)
    with pytest.raises(ValueError, match="divide"):
        sequential_to_staged(params_seq, 3)
    # Wrong tree kind, both directions.
    with pytest.raises(ValueError, match="block"):
        sequential_to_staged(
            sequential_to_staged(params_seq, 4), 4)
    with pytest.raises(ValueError, match="staged-backbone"):
        staged_to_sequential(params_seq)
    # Hand-built stages_n=8/per=1 staged tree over depth 8: Block shapes
    # would LOAD cleanly into the sequential model — the converter must
    # reject on architecture (alternating placement), not shape.
    feats = params_seq["params"]["features"]
    blocks = [feats[f"block{i}"] for i in range(8)]
    bad = {
        **params_seq,
        "params": {
            **params_seq["params"],
            "features": {
                k: v for k, v in feats.items() if not k.startswith("block")
            } | {"stages": {"b0": jax.tree.map(
                lambda *xs: jnp.stack(xs), *blocks)}},
        },
    }
    with pytest.raises(ValueError, match="architectures differ"):
        staged_to_sequential(bad)
