"""graftcast (train/precision.py + the flatcore dtype plumbing) gates.

The acceptance contract of the bf16-compute / f32-master-weight policy:

- the optimizer update is BIT-exact across policies given identical
  gradients (masters are f32 and the update never sees bf16);
- checkpoints are f32 tree-form and interchange between bf16 and f32
  runs in BOTH directions, bit-exact at the master-weight level;
- the compiled flat step materializes exactly ONE compute-shadow cast
  kernel per float dtype buffer (the per-leaf cast tree is gone) — the
  structural HLO proof, CPU-backend, outage-immune;
- the bf16 tiny-config train loss curve tracks f32 within a calibrated
  tolerance for C4 AND FPN (bf16 lowers fine on CPU XLA).

Budget note: the C4 fixtures reuse tests/test_flatcore.py's exact 64^2
micro-config so the f32 executables are persistent-cache hits; the bf16
(and FPN) steps are new programs and compile once per cache fill.
"""

import re
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.train import flatcore, precision
from mx_rcnn_tpu.train.step import make_train_step


def _c4_cfg(compute, **train_over):
    """tests/test_flatcore.py's 64^2 micro-config, policy selectable."""
    cfg = generate_config(
        "resnet50", "synthetic",
        **{
            "train.rpn_pre_nms_top_n": 128,
            "train.rpn_post_nms_top_n": 32,
            "train.batch_rois": 16,
            "train.max_gt_boxes": 4,
            "train.batch_images": 1,
            "network.anchor_scales": (2, 4),
            "image.pad_shape": (64, 64),
        })
    return cfg.with_updates(
        train=replace(cfg.train, **{"compute_dtype": compute, **train_over}))


def _fpn_cfg(compute):
    """tests/test_fpn.py's 128^2 tiny FPN config, policy selectable."""
    cfg = generate_config(
        "resnet50_fpn", "synthetic",
        **{
            "image.pad_shape": (128, 128),
            "train.batch_images": 1,
            "train.fpn_rpn_pre_nms_per_level": 64,
            "train.rpn_post_nms_top_n": 64,
            "train.batch_rois": 32,
            "train.max_gt_boxes": 8,
        })
    return cfg.with_updates(
        train=replace(cfg.train, compute_dtype=compute))


def _c4_batch():
    rs = np.random.RandomState(3)
    gt = np.zeros((1, 4, 4), np.float32)
    gt[:, 0] = [8, 8, 40, 40]
    valid = np.zeros((1, 4), bool)
    valid[:, 0] = True
    classes = np.zeros((1, 4), np.int32)
    classes[:, 0] = 1
    return {
        "image": jnp.asarray(rs.randn(1, 64, 64, 3).astype(np.float32)),
        "im_info": jnp.asarray([[64, 64, 1.0]], np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }


def _fpn_batch():
    rs = np.random.RandomState(5)
    return {
        "image": jnp.asarray(rs.randn(1, 128, 128, 3).astype(np.float32)),
        "im_info": jnp.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": jnp.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": jnp.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": jnp.asarray([[True, True] + [False] * 6]),
    }


def _fake_params(layers=4):
    """test_flatcore's hand-built tree: frozen conv0/norm + trainable
    layers; bbox_pred 8-wide = 2 classes x 4 (checkpoint fold/unfold)."""
    rs = np.random.RandomState(0)
    tree = {"conv0": {"kernel": rs.randn(3, 3, 3, 8).astype(np.float32)}}
    for i in range(layers):
        tree[f"layer{i:02d}"] = {
            "kernel": rs.randn(8, 8).astype(np.float32),
            "bias": rs.randn(8).astype(np.float32),
        }
    tree["norm"] = {"gamma": np.ones(8, np.float32),
                    "beta": np.zeros(8, np.float32)}
    tree["bbox_pred"] = {"kernel": rs.randn(8, 8).astype(np.float32),
                         "bias": rs.randn(8).astype(np.float32)}
    return {"params": tree}


def _leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# policy units (no compiles)
# ---------------------------------------------------------------------------

def test_policy_normalization_and_validation():
    assert precision.normalize_compute_dtype("bf16") == "bfloat16"
    assert precision.normalize_compute_dtype("BFloat16") == "bfloat16"
    assert precision.normalize_compute_dtype("f32") == "float32"
    assert precision.normalize_compute_dtype("float32") == "float32"
    with pytest.raises(ValueError, match="compute_dtype"):
        precision.normalize_compute_dtype("fp16")

    cfg = _c4_cfg("bf16")
    pol = precision.policy_of(cfg)
    assert pol.mixed and pol.short == "bf16"
    assert pol.master == "float32"
    assert precision.model_dtype(cfg) == jnp.bfloat16
    assert not precision.policy_of(_c4_cfg("f32")).mixed
    # a typo'd knob fails loudly at policy resolution (fit_detector
    # resolves it before any device work)
    bad = _c4_cfg("f32")
    bad = bad.with_updates(train=replace(bad.train, compute_dtype="f16"))
    with pytest.raises(ValueError):
        precision.policy_of(bad)


def test_island_param_predicate():
    # norm statistics/affine: FrozenBN leaves, bn*/downsample_bn and
    # norm*/dec_norm module params stay f32 master views
    for path in ("params/features/bn0/gamma",
                 "params/features/stage2/block0/bn1/moving_var",
                 "params/features/stage2/block0/downsample_bn/scale",
                 "params/features/block3/norm1/bias",
                 "params/dec_norm/scale"):
        assert precision.is_island_param(path), path
    # DETR's set-prediction heads are dtype=f32 Denses over island(hs):
    # tree mode computes them with UNCAST f32 weights, so flat mode must
    # serve master views — a shadow view would quantize the box/score
    # numerics the island contract keeps f32 (models/detr.py)
    for path in ("params/class_embed/kernel",
                 "params/bbox_mlp0/kernel",
                 "params/bbox_mlp1/bias",
                 "params/bbox_out/kernel"):
        assert precision.is_island_param(path), path
    # pos_embed is bilinearly RESIZED before its per-use cast (cast does
    # not commute with resize), and the SFP up4_ln is norm affine like
    # any other LayerNorm (models/vit.py)
    for path in ("params/features/pos_embed",
                 "params/neck/up4_ln/scale"):
        assert precision.is_island_param(path), path
    # conv/dense kernels and biases take the compute shadow — including
    # query_embed, whose per-use .astype(x.dtype) the shadow cast
    # commutes with
    for path in ("params/features/stage2/block0/conv1/kernel",
                 "params/rpn/rpn_conv/bias",
                 "params/head/fc6/kernel",
                 "params/cls_score/bias",
                 "params/query_embed"):
        assert not precision.is_island_param(path), path


def test_cast_buffers_one_convert_per_float_buffer():
    bufs = {"float32": jnp.ones(8, jnp.float32),
            "int32": jnp.arange(4, dtype=jnp.int32)}
    out = precision.cast_buffers(bufs, jnp.bfloat16)
    assert out["float32"].dtype == jnp.bfloat16
    assert out["int32"].dtype == jnp.int32  # non-float passes through


def test_flatcore_island_segments_read_master():
    """FrozenBN statistics stay f32 views under bf16 — the shadow covers
    conv/dense segments only (FlatCore.use_compute)."""
    cfg = _c4_cfg("bf16")
    params = _fake_params()
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=10)
    by_path = {s.path: uc
               for s, uc in zip(core.table.segments, core.use_compute)}
    assert not by_path["params/norm/gamma"]        # island
    assert not by_path["params/norm/beta"]         # island
    assert by_path["params/layer00/kernel"]        # compute shadow
    state = core.init_state(params)
    assert set(state.compute) == {"float32"}
    assert state.compute["float32"].dtype == jnp.bfloat16
    tree = core.params_view(state.flat, state.compute)
    assert tree["params"]["norm"]["gamma"].dtype == jnp.float32
    assert tree["params"]["layer00"]["kernel"].dtype == jnp.bfloat16
    # f32 policy: no shadow, plain master views — nothing changed
    state_f = flatcore.FlatCore(
        _c4_cfg("f32"), params, steps_per_epoch=10).init_state(params)
    assert state_f.compute == {}


# ---------------------------------------------------------------------------
# update bit-exactness + checkpoint interchange (no model compiles)
# ---------------------------------------------------------------------------

def test_update_bit_exact_across_policies_given_equal_grads():
    """The acceptance claim: masters are f32 and the optimizer update
    never sees bf16 — with gradients FORCED equal, the bf16-policy
    update is bit-for-bit the f32-policy update."""
    params = _fake_params()
    core_b = flatcore.FlatCore(_c4_cfg("bf16"), params, steps_per_epoch=10)
    core_f = flatcore.FlatCore(_c4_cfg("f32"), params, steps_per_epoch=10)
    rs = np.random.RandomState(7)
    grads = {d: jnp.asarray(rs.randn(int(n)).astype(d) * 1e-3)
             for d, n in core_f.table.sizes.items()}
    s_b, s_f = core_b.init_state(params), core_f.init_state(params)
    for _ in range(3):
        s_b = s_b.apply_gradients(grads)
        s_f = s_f.apply_gradients(grads)
    for d in s_f.flat:
        np.testing.assert_array_equal(np.asarray(s_b.flat[d]),
                                      np.asarray(s_f.flat[d]))
    for slot_b, slot_f in zip(s_b.slots, s_f.slots):
        for d in slot_f:
            np.testing.assert_array_equal(np.asarray(slot_b[d]),
                                          np.asarray(slot_f[d]))
    # and the shadow is exactly the cast of the updated masters
    np.testing.assert_array_equal(
        np.asarray(s_b.compute["float32"]),
        np.asarray(s_f.flat["float32"].astype(jnp.bfloat16)))


def test_checkpoint_interchange_bf16_f32_both_directions(tmp_path):
    """Checkpoints stay f32 tree-form: a bf16 run's save restores into
    an f32 run bit-exact at the master-weight level, and an f32 save
    restores into a bf16 run (shadow re-derived from the masters)."""
    from mx_rcnn_tpu.train.checkpoint import load_checkpoint, save_checkpoint
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state

    params = _fake_params()
    cfg_b, cfg_f = _c4_cfg("bf16"), _c4_cfg("f32")
    core_b = flatcore.FlatCore(cfg_b, params, steps_per_epoch=10)
    core_f = flatcore.FlatCore(cfg_f, params, steps_per_epoch=10)
    tx = build_optimizer(cfg_f, params, steps_per_epoch=10)
    # power-of-two stds: the checkpoint's bbox_pred unnormalize/
    # renormalize round-trip is bit-exact only then (the graftguard
    # parity convention, tests/_resilience_driver.py)
    kw = dict(means=cfg_f.train.bbox_means, stds=(0.5, 0.5, 0.25, 0.25),
              num_classes=2)
    rs = np.random.RandomState(9)
    grads = {d: jnp.asarray(rs.randn(int(n)).astype(d) * 1e-3)
             for d, n in core_f.table.sizes.items()}

    # bf16 run trains a step and saves — on-disk form must be f32 tree
    s_b = core_b.init_state(params).apply_gradients(grads)
    p_save, o_save = core_b.tree_state(s_b)
    assert all(np.asarray(x).dtype == np.float32
               for x in jax.tree_util.tree_leaves(p_save))
    save_checkpoint(str(tmp_path / "bf16run"), 1, p_save, o_save, **kw)

    # -> f32 run: loaded masters bit-exact vs the live bf16 state
    p_l, o_l = load_checkpoint(
        str(tmp_path / "bf16run"), 1, template={"params": params},
        opt_state_template=tx.init(params), **kw)
    resumed_f = core_f.flatten_state(
        create_train_state(p_l, tx).replace(
            opt_state=o_l, step=jnp.asarray(1, jnp.int32)))
    for d in s_b.flat:
        np.testing.assert_array_equal(np.asarray(resumed_f.flat[d]),
                                      np.asarray(s_b.flat[d]))
    assert resumed_f.compute == {}

    # f32 run saves -> bf16 run restores: masters bit-exact, shadow is
    # the cast of the restored masters
    s_f = core_f.init_state(params).apply_gradients(grads)
    pf, of = core_f.tree_state(s_f)
    save_checkpoint(str(tmp_path / "f32run"), 1, pf, of, **kw)
    p_l2, o_l2 = load_checkpoint(
        str(tmp_path / "f32run"), 1, template={"params": params},
        opt_state_template=tx.init(params), **kw)
    resumed_b = core_b.flatten_state(
        create_train_state(p_l2, tx).replace(
            opt_state=o_l2, step=jnp.asarray(1, jnp.int32)))
    for d in s_f.flat:
        np.testing.assert_array_equal(np.asarray(resumed_b.flat[d]),
                                      np.asarray(s_f.flat[d]))
    np.testing.assert_array_equal(
        np.asarray(resumed_b.compute["float32"]),
        np.asarray(resumed_b.flat["float32"].astype(jnp.bfloat16)))


# ---------------------------------------------------------------------------
# compiled-step gates: one cast kernel + loss-curve parity (C4, FPN)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def c4_steps():
    """Shared C4 fixtures: (batch, f32 pair, bf16 pair, bf16 HLO text).
    The bf16 step is AOT-compiled once — the loss gate runs it and the
    HLO gate reads it."""
    from mx_rcnn_tpu.models.faster_rcnn import build_model, init_params

    cfg_f, cfg_b = _c4_cfg("f32"), _c4_cfg("bf16")
    model_f, model_b = build_model(cfg_f), build_model(cfg_b)
    params = init_params(model_f, cfg_f, jax.random.PRNGKey(0))
    core_f = flatcore.FlatCore(cfg_f, params, steps_per_epoch=10)
    core_b = flatcore.FlatCore(cfg_b, params, steps_per_epoch=10)
    batch = _c4_batch()
    step_f = make_train_step(model_f, cfg_f, donate=False, flat_core=core_f)
    step_b = make_train_step(model_b, cfg_b, donate=False, flat_core=core_b)
    compiled_b = step_b.lower(core_b.init_state(params), batch,
                              jax.random.PRNGKey(11)).compile()
    return {"batch": batch, "params": params,
            "core_f": core_f, "core_b": core_b,
            "step_f": step_f, "compiled_b": compiled_b,
            "hlo": compiled_b.as_text()}


def test_bf16_one_cast_kernel_per_dtype_buffer(c4_steps):
    """The structural proof (CPU backend, outage-immune): the compiled
    bf16 flat step materializes the compute shadow with EXACTLY ONE
    buffer-sized cast kernel — fusion bodies may show lazy whole-buffer
    converts (slice-scoped, never materialized), so the gate counts
    ENTRY-level producers of a full bf16 buffer. A per-leaf cast tree
    has no buffer-sized bf16 producer at all and per-leaf programs
    re-convert in every consumer; one materialized producer == the
    shadow, written once per step."""
    n = int(c4_steps["core_b"].table.sizes["float32"])
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", c4_steps["hlo"], re.S | re.M)
    assert m, "no ENTRY computation in HLO text"
    producers = [
        line.strip() for line in m.group(1).splitlines()
        if re.match(rf"\s*%\S+ = bf16\[{n}\]", line)
        and "parameter(" not in line]
    assert len(producers) == 1, producers
    # and it is the convert (possibly wrapped in a parallel fusion call)
    assert "convert" in producers[0], producers[0]


def test_bf16_loss_curve_matches_f32_c4(c4_steps):
    """3-step tiny-config loss curve, bf16 vs f32 (flat mode both).
    Calibrated gate: observed per-step relative gap <= ~6e-3 on CPU XLA
    (discrete proposal/sampling selections may flip under bf16 scores,
    so this is a tolerance, not bit-exactness); 3x margin -> 2e-2."""
    batch, params = c4_steps["batch"], c4_steps["params"]
    s_f = c4_steps["core_f"].init_state(params)
    s_b = c4_steps["core_b"].init_state(params)
    keys = jax.random.split(jax.random.PRNGKey(11), 3)
    gaps = []
    for i in range(3):
        k = keys[i]
        s_f, m_f = c4_steps["step_f"](s_f, batch, k)
        s_b, m_b = c4_steps["compiled_b"](s_b, batch, k)
        lf, lb = float(m_f["TotalLoss"]), float(m_b["TotalLoss"])
        assert np.isfinite(lf) and np.isfinite(lb)
        gaps.append(abs(lb - lf) / max(abs(lf), 1e-6))
    assert max(gaps) < 2e-2, gaps


def test_bf16_loss_curve_matches_f32_fpn():
    """Same gate for the FPN family (multi-level proposals + approx
    top-k preset): 2 steps at the tests/test_fpn.py tiny geometry.
    Tolerance is looser than C4 — the per-level top-k membership at
    k=64 of ~3k scores is more selection-sensitive under bf16."""
    from mx_rcnn_tpu.models.zoo import build_model, forward_train, init_params

    cfg_f, cfg_b = _fpn_cfg("f32"), _fpn_cfg("bf16")
    model_f, model_b = build_model(cfg_f), build_model(cfg_b)
    params = init_params(model_f, cfg_f, jax.random.PRNGKey(0))
    core_f = flatcore.FlatCore(cfg_f, params, steps_per_epoch=10)
    core_b = flatcore.FlatCore(cfg_b, params, steps_per_epoch=10)
    batch = _fpn_batch()
    step_f = make_train_step(model_f, cfg_f, donate=False,
                             forward_fn=forward_train, flat_core=core_f)
    step_b = make_train_step(model_b, cfg_b, donate=False,
                             forward_fn=forward_train, flat_core=core_b)
    s_f, s_b = core_f.init_state(params), core_b.init_state(params)
    keys = jax.random.split(jax.random.PRNGKey(13), 2)
    gaps = []
    for i in range(2):
        k = keys[i]
        s_f, m_f = step_f(s_f, batch, k)
        s_b, m_b = step_b(s_b, batch, k)
        lf, lb = float(m_f["TotalLoss"]), float(m_b["TotalLoss"])
        assert np.isfinite(lf) and np.isfinite(lb)
        gaps.append(abs(lb - lf) / max(abs(lf), 1e-6))
    assert max(gaps) < 5e-2, gaps
