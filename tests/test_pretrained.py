"""Pretrained-weight import path (utils/pretrained.py, utils/torch_convert.py).

Reference: rcnn/utils/load_model.py::load_param over ImageNet .params +
script/get_pretrained_model.sh (SURVEY.md §3). Offline, so the torch-side
inputs are SYNTHETIC state_dicts built with torchvision's exact naming and
shapes; the import side validates every array against the real flax param
tree, so a wrong transpose, name map, or routing rule fails here.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

import jax

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.utils.pretrained import (
    flatten_params,
    import_pretrained,
    load_params_npz,
    save_params_npz,
    unflatten_params,
)
from mx_rcnn_tpu.utils.torch_convert import (
    convert,
    convert_torchvision_resnet,
    convert_torchvision_vgg16,
)

RESNET_BLOCKS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}


def _he(rs, *shape):
    """Conv weight at torchvision scale — He init over fan_in (O,I,kH,kW).
    Realistic magnitudes matter: the readiness drill trains through these
    with frozen BN, where unit-std weights explode in a 100-layer trunk."""
    fan_in = int(np.prod(shape[1:]))
    return (rs.randn(*shape) * np.sqrt(2.0 / fan_in)).astype(np.float32)


def fake_torch_resnet(depth: int, rs: np.random.RandomState):
    """state_dict with torchvision resnet naming/shapes (numpy values)."""
    sd = {}

    def bn(prefix, ch):
        sd[f"{prefix}.weight"] = (1 + 0.1 * rs.randn(ch)).astype(np.float32)
        sd[f"{prefix}.bias"] = (0.1 * rs.randn(ch)).astype(np.float32)
        sd[f"{prefix}.running_mean"] = (0.1 * rs.randn(ch)).astype(np.float32)
        sd[f"{prefix}.running_var"] = (1 + 0.1 * rs.rand(ch)).astype(np.float32)
        sd[f"{prefix}.num_batches_tracked"] = np.asarray(1)

    sd["conv1.weight"] = _he(rs, 64, 3, 7, 7)
    bn("bn1", 64)
    in_ch = 64
    for s, (blocks, width) in enumerate(
            zip(RESNET_BLOCKS[depth], (64, 128, 256, 512)), start=1):
        for b in range(blocks):
            p = f"layer{s}.{b}"
            sd[f"{p}.conv1.weight"] = _he(rs, width, in_ch, 1, 1)
            bn(f"{p}.bn1", width)
            sd[f"{p}.conv2.weight"] = _he(rs, width, width, 3, 3)
            bn(f"{p}.bn2", width)
            sd[f"{p}.conv3.weight"] = _he(rs, width * 4, width, 1, 1)
            bn(f"{p}.bn3", width * 4)
            if b == 0:
                sd[f"{p}.downsample.0.weight"] = _he(rs, width * 4, in_ch, 1, 1)
                bn(f"{p}.downsample.1", width * 4)
            in_ch = width * 4
    sd["fc.weight"] = (0.01 * rs.randn(1000, 2048)).astype(np.float32)
    sd["fc.bias"] = np.zeros(1000, np.float32)
    return sd


VGG16_TORCH_CONV_IDX = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28)
VGG16_WIDTHS = (64, 64, 128, 128, 256, 256, 256, 512, 512, 512, 512, 512, 512)


def fake_torch_vgg16(rs: np.random.RandomState):
    sd = {}
    in_ch = 3
    for idx, width in zip(VGG16_TORCH_CONV_IDX, VGG16_WIDTHS):
        sd[f"features.{idx}.weight"] = _he(rs, width, in_ch, 3, 3)
        sd[f"features.{idx}.bias"] = (0.1 * rs.randn(width)).astype(np.float32)
        in_ch = width
    sd["classifier.0.weight"] = (0.01 * rs.randn(4096, 512 * 7 * 7)).astype(np.float32)
    sd["classifier.0.bias"] = (0.1 * rs.randn(4096)).astype(np.float32)
    sd["classifier.3.weight"] = (0.01 * rs.randn(4096, 4096)).astype(np.float32)
    sd["classifier.3.bias"] = (0.1 * rs.randn(4096)).astype(np.float32)
    sd["classifier.6.weight"] = (0.01 * rs.randn(1000, 4096)).astype(np.float32)
    sd["classifier.6.bias"] = np.zeros(1000, np.float32)
    return sd


def tiny_template(network: str):
    """Returns (cfg, bare param dict) — init_params wraps in {'params': …};
    import_pretrained accepts both forms (the wrapped form is what
    fit_detector passes; test_wrapped_template_form covers it)."""
    cfg = generate_config(network, "synthetic",
                          **{"image.pad_shape": (128, 128),
                             "train.batch_images": 1})
    model = zoo.build_model(cfg)
    return cfg, zoo.init_params(model, cfg, jax.random.PRNGKey(0))["params"]


def test_flatten_unflatten_roundtrip(rng):
    tree = {"a": {"b": rng.randn(2, 3), "c": {"d": rng.randn(4)}},
            "e": rng.randn(1)}
    flat = flatten_params(tree)
    assert set(flat) == {"a/b", "a/c/d", "e"}
    back = unflatten_params(flat)
    np.testing.assert_array_equal(back["a"]["c"]["d"], tree["a"]["c"]["d"])


def test_npz_roundtrip(tmp_path, rng):
    tree = {"x": {"kernel": rng.randn(3, 3).astype(np.float32)}}
    path = str(tmp_path / "w.npz")
    save_params_npz(path, tree)
    flat = load_params_npz(path)
    np.testing.assert_array_equal(flat["x/kernel"], tree["x"]["kernel"])


def test_resnet50_import_c4(tmp_path, rng):
    """Full torchvision-style resnet50 → C4 detector: every backbone leaf
    (features/* AND the stage-4 head) is loaded; detection heads keep init."""
    sd = fake_torch_resnet(50, rng)
    path = str(tmp_path / "r50.npz")
    convert("resnet50", sd, path)

    _, params = tiny_template("resnet50")
    before = flatten_params(params)
    loaded, report = import_pretrained(path, params)
    after = flatten_params(loaded)

    feat_keys = [k for k in after if k.startswith("features/")]
    head_keys = [k for k in after if k.startswith("head/stage4/")]
    assert feat_keys and head_keys
    for k in feat_keys + head_keys:
        assert not np.array_equal(after[k], before[k]), f"{k} not loaded"
    for k in after:
        if k.startswith(("rpn/", "cls_score", "bbox_pred")):
            np.testing.assert_array_equal(after[k], before[k])
    # the ImageNet fc classifier must have been dropped at convert time
    assert not any("fc." in u or u.startswith("fc/") for u in report.unused)
    assert not report.uninitialized or all(
        k.startswith(("rpn/", "cls_score", "bbox_pred"))
        for k in report.uninitialized)


def test_resnet50_import_fpn_routes_stage4_to_features(tmp_path, rng):
    sd = fake_torch_resnet(50, rng)
    path = str(tmp_path / "r50.npz")
    convert("resnet50", sd, path)
    _, params = tiny_template("resnet50_fpn")
    loaded, report = import_pretrained(path, params)
    assert any(k.startswith("features/stage4/") for k in report.loaded)
    # FPN neck + heads keep their init, trunk fully covered
    assert not any(k.startswith("features/") for k in report.uninitialized)


def test_resnet101_conversion_covers_template(tmp_path, rng):
    sd = fake_torch_resnet(101, rng)
    path = str(tmp_path / "r101.npz")
    convert("resnet101", sd, path)
    _, params = tiny_template("resnet101")
    loaded, report = import_pretrained(path, params)
    assert not any(k.startswith(("features/", "head/"))
                   for k in report.uninitialized)


def test_vgg16_import(tmp_path, rng):
    sd = fake_torch_vgg16(rng)
    path = str(tmp_path / "vgg16.npz")
    convert("vgg16", sd, path)
    _, params = tiny_template("vgg")
    before = flatten_params(params)
    loaded, report = import_pretrained(path, params)
    after = flatten_params(loaded)
    for k in after:
        if k.startswith("features/") or k.startswith("head/fc"):
            assert not np.array_equal(after[k], before[k]), f"{k} not loaded"
    assert not report.unused  # every converted array found a home


def test_vgg_fc6_flatten_order_permute(rng):
    """fc6 applied to our (H,W,C)-flattened pool must equal torch's linear
    on the same features flattened (C,H,W) — the permute is load-bearing."""
    sd = fake_torch_vgg16(rng)
    flat = convert_torchvision_vgg16(sd)
    feat_hwc = rng.randn(7, 7, 512).astype(np.float32)
    ours = feat_hwc.reshape(-1) @ flat["fc6/kernel"] + flat["fc6/bias"]
    torch_in = feat_hwc.transpose(2, 0, 1).reshape(-1)  # (C,H,W) flatten
    theirs = sd["classifier.0.weight"] @ torch_in + sd["classifier.0.bias"]
    # 25088-term float32 dots in two accumulation orders
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-2)


def test_resnet_conv_transpose_is_functional(rng):
    """HWIO conversion: jax conv with converted kernel == torch-layout
    reference conv (spot check on the stem 7x7)."""
    sd = fake_torch_resnet(50, rng)
    flat = convert_torchvision_resnet(sd)
    x = rng.randn(1, 16, 16, 3).astype(np.float32)
    y = jax.lax.conv_general_dilated(
        x, flat["conv0/kernel"], window_strides=(2, 2),
        padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # reference: NCHW conv with the original OIHW kernel
    y_ref = jax.lax.conv_general_dilated(
        x.transpose(0, 3, 1, 2), sd["conv1.weight"], window_strides=(2, 2),
        padding=[(3, 3), (3, 3)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(y_ref).transpose(0, 2, 3, 1),
                               rtol=2e-4, atol=2e-4)


def test_strict_backbone_rejects_partial_manifest(tmp_path, rng):
    sd = fake_torch_resnet(50, rng)
    flat = convert_torchvision_resnet(sd)
    partial = {k: v for k, v in flat.items() if not k.startswith("stage3")}
    path = str(tmp_path / "partial.npz")
    save_params_npz(path, partial)
    _, params = tiny_template("resnet50")
    with pytest.raises(ValueError, match="backbone leaves not covered"):
        import_pretrained(path, params)
    loaded, report = import_pretrained(path, params, strict_backbone=False)
    assert any(k.startswith("features/stage3") for k in report.uninitialized)


def test_strict_covers_c4_head_stage4(tmp_path, rng):
    """A manifest missing stage4 must FAIL against a C4 model even though
    every features/ leaf loads — stage4 is trunk there, routed to head/
    (the classic silently-half-loaded-trunk trap)."""
    sd = fake_torch_resnet(50, rng)
    flat = convert_torchvision_resnet(sd)
    no_s4 = {k: v for k, v in flat.items() if not k.startswith("stage4")}
    path = str(tmp_path / "no_s4.npz")
    save_params_npz(path, no_s4)
    _, params = tiny_template("resnet50")
    with pytest.raises(ValueError, match="backbone leaves not covered"):
        import_pretrained(path, params)
    # ...but the same truncated manifest is fine for FPN (stage4 under
    # features/ would be missing there too — also caught):
    _, fpn_params = tiny_template("resnet50_fpn")
    with pytest.raises(ValueError, match="backbone leaves not covered"):
        import_pretrained(path, fpn_params)


def test_backbone_shape_mismatch_raises(tmp_path, rng):
    """A resnet50 manifest against a resnet101 model must fail loudly
    (stage3 block counts differ → first shape clash raises)."""
    sd = fake_torch_resnet(50, rng)
    path = str(tmp_path / "r50.npz")
    convert("resnet50", sd, path)
    _, params = tiny_template("resnet101")
    with pytest.raises(ValueError):
        import_pretrained(path, params)


def test_class_count_mismatch_heads_keep_init(tmp_path, rng):
    """Full-tree npz from an N-class model into an M-class model: heads
    skip (reference load_param behavior), trunk loads."""
    _, params = tiny_template("resnet50")
    path = str(tmp_path / "full.npz")
    # Perturb so "loaded" is detectable, then grow cls_score out dim.
    full = flatten_params(params)
    full = {k: v + 1.0 for k, v in full.items()}
    full["cls_score/kernel"] = rng.randn(2048, 99).astype(np.float32)
    full["cls_score/bias"] = rng.randn(99).astype(np.float32)
    save_params_npz(path, full)
    loaded, report = import_pretrained(path, params)
    assert len(report.skipped) == 2
    after = flatten_params(loaded)
    np.testing.assert_array_equal(after["cls_score/bias"],
                                  flatten_params(params)["cls_score/bias"])
    assert any(k.startswith("features/") for k in report.loaded)


def test_garbage_npz_rejected(tmp_path, rng):
    path = str(tmp_path / "junk.npz")
    save_params_npz(path, {"not/a/real/key": rng.randn(3)})
    _, params = tiny_template("resnet50")
    with pytest.raises(ValueError, match="no key"):
        import_pretrained(path, params, strict_backbone=False)


@pytest.mark.slow
def test_readiness_drill_r101(tmp_path, rng):
    """Launch-readiness drill for the flagship R101 recipe: convert a
    (synthetic) torchvision-style ImageNet checkpoint → train 1 epoch from
    it with the PRETRAINED profile (frozen-BN + frozen prefix — only sound
    with imported statistics) → eval through the test.py path. This is the
    exact sequence the real COCO run will execute when data appears; only
    the weights and images are synthetic."""
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.data.loader import TestLoader
    from mx_rcnn_tpu.evaluation.tester import Predictor, pred_eval
    from mx_rcnn_tpu.tools.train import fit_detector

    npz = str(tmp_path / "r101_imagenet.npz")
    convert("resnet101", fake_torch_resnet(101, rng), npz)

    # The flagship config at drill shapes. norm/freeze_at stay at the
    # pretrained-profile defaults (frozen_bn, freeze_at=2).
    cfg = generate_config("resnet101", "synthetic", **{
        "image.pad_shape": (128, 128),
        "image.scales": ((128, 128),),
        "network.anchor_scales": (2, 4, 8),
        "train.rpn_pre_nms_top_n": 256,
        "train.rpn_post_nms_top_n": 64,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
        "train.batch_images": 1,
        "train.flip": False,
        "test.rpn_pre_nms_top_n": 128,
        "test.rpn_post_nms_top_n": 32,
        "test.max_per_image": 8,
    })
    ds = SyntheticDataset("train", num_images=4, image_size=128,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    roidb = ds.gt_roidb()

    history = []
    params = fit_detector(
        cfg, roidb, prefix=str(tmp_path / "ckpt"), end_epoch=1, frequent=1000,
        epoch_callback=lambda e, s, b: history.append(b.get()["TotalLoss"]),
        pretrained_npz=npz, seed=0)
    assert np.isfinite(history).all(), history

    # The frozen prefix (stem + stage1) must be bit-identical to the
    # imported ImageNet weights after training — freezing is structural.
    after = flatten_params(params["params"] if "params" in params else params)
    manifest = load_params_npz(npz)
    np.testing.assert_array_equal(np.asarray(after["features/conv0/kernel"]),
                                  manifest["conv0/kernel"])
    np.testing.assert_array_equal(
        np.asarray(after["features/stage1/block0/conv1/kernel"]),
        manifest["stage1/block0/conv1/kernel"])
    # ...and stage3 must have trained away from the import.
    assert not np.array_equal(
        np.asarray(after["features/stage3/block0/conv1/kernel"]),
        manifest["stage3/block0/conv1/kernel"])

    model = zoo.build_model(cfg)
    result = pred_eval(Predictor(model, params, cfg),
                       TestLoader(roidb, cfg, batch_size=1), ds, thresh=0.05)
    assert "mAP" in result and np.isfinite(result["mAP"])


def test_wrapped_template_form(tmp_path, rng):
    """fit_detector passes the {'params': …} wrapping; the import must
    accept it and return the same wrapping."""
    sd = fake_torch_resnet(50, rng)
    path = str(tmp_path / "r50.npz")
    convert("resnet50", sd, path)
    _, bare = tiny_template("resnet50")
    loaded, _ = import_pretrained(path, {"params": bare})
    assert set(loaded) == {"params"}
    np.testing.assert_array_equal(
        flatten_params(loaded["params"])["features/conv0/kernel"],
        convert_torchvision_resnet(sd)["conv0/kernel"])
