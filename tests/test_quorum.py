"""graftquorum (resilience/quorum.py) gates — multi-host coordinated
resilience, exercised for real on CPU.

Two layers:

- **protocol units** (tier-1, no device work): FileKVStore atomicity,
  deadline-bounded barriers, generation-numbered heal rounds with
  exclusion and min-fraction, the two-phase coordinated stop under
  drift, the chaos multi-host keys, and the simulated-host identity
  wrappers;
- **N-process trainer gates** (``slow`` — each spawns full training
  subprocesses): the ISSUE acceptance scenarios. Each "host" is a
  separate CPU process running the FULL replicated computation
  (deterministic, bit-identical trajectories — no cross-process
  collectives) whose coordination identity comes from
  ``MXRCNN_SIM_PROCESS_ID``, coordinating through a shared FileKVStore
  exactly as a pod fleet would through jax.distributed's KV service:

  * coordinated preemption: SIGTERM one of two hosts -> BOTH drain to
    the agreed boundary, exactly ONE published save (complete host set
    in graft_meta.json), both exit rc 75, and a dual ``--resume auto``
    reaches params BIT-exact vs an uninterrupted run (tree and flat);
  * multi-host heal with exclusion: both hosts lose devices, one is
    chaos-armed to miss the heal rendezvous -> survivors seal a quorum
    without it, the run continues, the excluded host exits rc 75;
  * elastic grow / rescale (in-process, single host): a heal that
    re-acquires MORE devices grows past the nominal footprint; one too
    deep for the global batch rescales it (rows-per-device constant,
    schedule rebased).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.obs import open_event_log, report
from mx_rcnn_tpu.parallel.partition import elastic_mesh_spec
from mx_rcnn_tpu.resilience import (
    RESUMABLE_RC,
    CoordinatedStop,
    FileKVStore,
    PreemptionExit,
    Quorum,
    QuorumError,
    QuorumExcludedError,
    chaos,
)
from mx_rcnn_tpu.train.checkpoint import (
    checkpoint_meta,
    latest_checkpoint,
    save_checkpoint,
)

import _resilience_driver as driver

pytestmark = pytest.mark.chaos

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO_ROOT, "tests", "_resilience_driver.py")


def _subprocess_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    for k in ("MX_RCNN_CHAOS", "MXRCNN_SIM_PROCESS_ID",
              "MXRCNN_SIM_NUM_PROCESSES"):
        env.pop(k, None)
    env.update(extra)
    return env


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    monkeypatch.delenv("MXRCNN_SIM_PROCESS_ID", raising=False)
    monkeypatch.delenv("MXRCNN_SIM_NUM_PROCESSES", raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _quorum(store, index, count, **kw):
    kw.setdefault("timeout_s", 2.0)
    kw.setdefault("poll_s", 0.005)
    return Quorum(store, index, count, **kw)


# ---------------------------------------------------------------------------
# KV store
# ---------------------------------------------------------------------------

def test_file_kv_store_set_get_propose(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    assert store.get("a/b") is None
    store.set("a/b", "1")
    assert store.get("a/b") == "1"
    store.set("a/b", "2")  # set = last-writer-wins
    assert store.get("a/b") == "2"
    # propose = FIRST-writer-wins: the loser gets the winning value back
    assert store.propose("stop/req/value", "5") == "5"
    assert store.propose("stop/req/value", "9") == "5"
    assert store.get("stop/req/value") == "5"


def test_file_kv_store_rejects_escaping_keys(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    with pytest.raises(ValueError, match="escapes store root"):
        store.set("../outside", "x")


# ---------------------------------------------------------------------------
# barriers
# ---------------------------------------------------------------------------

def test_barrier_all_arrive(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    qs = [_quorum(store, i, 3) for i in range(3)]
    results = {}

    def arrive(q):
        results[q.index] = q.barrier("save/1")

    threads = [threading.Thread(target=arrive, args=(q,)) for q in qs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r == {0, 1, 2} for r in results.values()), results


def test_barrier_partial_set_on_deadline(tmp_path):
    """A host that never arrives does NOT hang the others forever — the
    deadline returns the partial set and the caller decides."""
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 2, timeout_s=0.3)
    arrived = q0.barrier("save/1")
    assert arrived == {0}


def test_barrier_waits_only_for_active_hosts(tmp_path):
    """After an exclusion shrinks ``active``, later barriers must not
    deadline on the dead host (else every epoch save eats the timeout)."""
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 2, timeout_s=5.0)
    q0.active = {0}
    t0 = time.monotonic()
    assert q0.barrier("epoch/3") == {0}
    assert time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# heal rounds
# ---------------------------------------------------------------------------

def test_heal_round_agrees_min_devices_topology(tmp_path):
    """Both hosts arrive with different re-acquired capacity: the leader
    seals the spec derived from the MINIMUM, and both adopt it."""
    store = FileKVStore(str(tmp_path / "kv"))
    qs = [_quorum(store, i, 2) for i in range(2)]
    outcomes = {}

    def heal(q, n_dev):
        outcomes[q.index] = q.heal_round(
            0, n_dev, lambda d, n: f"{d}x1")

    threads = [threading.Thread(target=heal, args=(qs[0], 8)),
               threading.Thread(target=heal, args=(qs[1], 6))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outcomes[0].spec == outcomes[1].spec == "6x1"
    assert outcomes[0].devices == 6
    assert outcomes[0].arrived == [0, 1] and outcomes[0].excluded == []


def test_heal_round_seals_without_straggler_then_excludes_it(tmp_path):
    """Host 1 misses the deadline: host 0 seals a one-host quorum and
    continues; host 1, arriving late at the SAME generation, discovers
    the seal without its index and raises QuorumExcludedError (-> the
    trainer turns that into a resumable rc-75 exit)."""
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 2, timeout_s=0.3)
    outcome = q0.heal_round(0, 4, lambda d, n: f"{d}x1")
    assert outcome.arrived == [0] and outcome.excluded == [1]
    assert q0.active == {0}

    q1 = _quorum(store, 1, 2, timeout_s=0.3)
    with pytest.raises(QuorumExcludedError, match="missed heal generation"):
        q1.heal_round(0, 4, lambda d, n: f"{d}x1")


def test_heal_round_below_min_fraction_aborts(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 3, timeout_s=0.3, min_fraction=0.9)
    with pytest.raises(QuorumError, match="min fraction"):
        q0.heal_round(0, 4, lambda d, n: f"{d}x1")


# ---------------------------------------------------------------------------
# coordinated stop
# ---------------------------------------------------------------------------

def test_coordinated_stop_agrees_max_under_drift(tmp_path):
    """Host 0 is signaled at boundary 5 while host 1 already drifted to
    boundary 7: the agreed stop is 7 on BOTH hosts — no host is asked to
    stop at a boundary it already passed."""
    store = FileKVStore(str(tmp_path / "kv"))
    s0 = CoordinatedStop(_quorum(store, 0, 2))
    s1 = CoordinatedStop(_quorum(store, 1, 2))
    s0.request(5)
    agreed = {}

    def check(s, boundary):
        agreed[s.quorum.index] = s.check(boundary)

    threads = [threading.Thread(target=check, args=(s0, 5)),
               threading.Thread(target=check, args=(s1, 7))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert agreed == {0: 7, 1: 7}
    # cached thereafter: later boundaries return the same agreement
    assert s0.check(6) == 7


def test_coordinated_stop_check_is_none_without_request(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    s0 = CoordinatedStop(_quorum(store, 0, 2))
    assert s0.check(3) is None  # the un-signaled steady state: one get


# ---------------------------------------------------------------------------
# chaos multi-host keys + simulated-host identity
# ---------------------------------------------------------------------------

def test_chaos_parse_multihost_keys():
    spec = chaos.parse("host_die_at_step=1:4 barrier_timeout_at=quorum_barrier")
    assert spec.host_die_at_step == "1:4"
    assert spec.barrier_timeout_at == "quorum_barrier" and spec.active
    with pytest.raises(ValueError, match="H:K"):
        chaos.parse("host_die_at_step=four")
    with pytest.raises(ValueError, match="registered"):
        chaos.parse("barrier_timeout_at=not_a_site")


def test_chaos_barrier_timeout_blocks_arrival(tmp_path, monkeypatch):
    """Armed ``barrier_timeout_at=quorum_barrier``: this process does
    not arrive (a host hung past the deadline), so peers see a partial
    set — the exclusion path, injected deterministically."""
    monkeypatch.setenv(chaos.ENV_VAR, "barrier_timeout_at=quorum_barrier")
    chaos.reset()
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 1, timeout_s=0.2)
    assert q0.barrier("save/1") == set()


def test_chaos_barrier_timeout_host_scoping(tmp_path, monkeypatch):
    """``H:site`` scoping: armed for host 1, host 0 arrives normally."""
    monkeypatch.setenv(chaos.ENV_VAR, "barrier_timeout_at=1:quorum_barrier")
    monkeypatch.setenv("MXRCNN_SIM_PROCESS_ID", "0")
    chaos.reset()
    store = FileKVStore(str(tmp_path / "kv"))
    q0 = _quorum(store, 0, 1)
    assert q0.barrier("save/1") == {0}


def test_sim_process_identity_wrappers(monkeypatch):
    from mx_rcnn_tpu.parallel.distributed import (
        is_primary, process_count, process_index)

    monkeypatch.setenv("MXRCNN_SIM_PROCESS_ID", "3")
    monkeypatch.setenv("MXRCNN_SIM_NUM_PROCESSES", "4")
    assert process_index() == 3 and process_count() == 4
    assert not is_primary()
    monkeypatch.setenv("MXRCNN_SIM_PROCESS_ID", "0")
    assert is_primary()


# ---------------------------------------------------------------------------
# torn-save detection (satellite a)
# ---------------------------------------------------------------------------

def test_latest_checkpoint_skips_torn_multihost_emergency(tmp_path, caplog):
    """An emergency save whose meta records FEWER hosts than expected
    (a host died before the publication barrier) is skipped with a
    warning; resume falls back to the newest COMPLETE state."""
    prefix = str(tmp_path / "run")
    w = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    save_checkpoint(prefix, 1, w,
                    meta={"epoch": 1, "dispatch": None,
                          "hosts": [0, 1], "host_count": 2})
    save_checkpoint(prefix, 1, w, dispatch=2,
                    meta={"epoch": 1, "dispatch": 2,
                          "hosts": [0], "host_count": 2})  # torn
    found = latest_checkpoint(prefix)
    assert found == (1, None), found
    assert any("torn" in r.message for r in caplog.records)

    # the same emergency save with a COMPLETE host set is trusted
    save_checkpoint(prefix, 1, w, dispatch=3,
                    meta={"epoch": 1, "dispatch": 3,
                          "hosts": [0, 1], "host_count": 2})
    assert latest_checkpoint(prefix) == (1, 3)


# ---------------------------------------------------------------------------
# per-host event streams + report folding
# ---------------------------------------------------------------------------

def test_report_folds_per_host_streams_and_quorum_summary(tmp_path):
    d = str(tmp_path / "obs")
    log0 = open_event_log(d, process_index=0)
    log1 = open_event_log(d, process_index=1)
    assert os.path.basename(log1.path) == "events_p1.jsonl"
    log0.emit("quorum", kind="heal", generation=0, hosts=[0],
              excluded=[1], devices=4, spec="4x1")
    log1.emit("quorum", kind="excluded", error="missed heal generation 0")
    log0.close()
    log1.close()

    events = report.load_events(d)
    assert [e["process"] for e in events if e["type"] == "quorum"] \
        in ([0, 1], [1, 0])
    summary = report.summarize(events)
    assert summary["quorum"]["rounds"] == 2
    assert summary["quorum"]["hosts"] == 2
    assert summary["quorum"]["excluded"] == [1]
    assert "quorum" in report.render(summary)


# ---------------------------------------------------------------------------
# elastic phase 2 spec derivation (parallel/partition.py)
# ---------------------------------------------------------------------------

def test_elastic_mesh_spec_grow_and_rescale_modes():
    # shrink (default) never grows past the nominal footprint
    assert elastic_mesh_spec(2, 1, 8, 4) == "2x1"
    # grow: onto the largest micro-batch divisor the devices allow
    assert elastic_mesh_spec(2, 1, 8, 4, mode="grow") == "4x1"
    assert elastic_mesh_spec(2, 1, 3, 4, mode="grow") == "2x1"
    # rescale: a non-divisor count is taken as-is (the trainer rebuilds
    # the loader and rebases the schedule)
    assert elastic_mesh_spec(4, 1, 3, 4, mode="rescale") == "3x1"
    assert elastic_mesh_spec(4, 1, 8, 4, mode="rescale") == "4x1"
    with pytest.raises(ValueError, match="elastic mode"):
        elastic_mesh_spec(4, 1, 3, 4, mode="stretch")


# ---------------------------------------------------------------------------
# multi-host trainer gates (the ISSUE acceptance scenarios)
# ---------------------------------------------------------------------------

def _spawn_host(idx, n_hosts, prefix, kv_dir, *, resume=None, flat=False,
                obs_dir="", chaos_env=None, end_epoch=2, timeout_s=120):
    cmd = [sys.executable, DRIVER, "--fit", prefix,
           "--end-epoch", str(end_epoch),
           "--sim-host", str(idx), "--sim-hosts", str(n_hosts),
           "--quorum-dir", kv_dir, "--quorum-timeout", str(timeout_s)]
    if resume:
        cmd += ["--resume", resume] if resume != True else ["--resume"]
    if flat:
        cmd += ["--flat"]
    if obs_dir:
        cmd += ["--obs-dir", obs_dir]
    env = _subprocess_env(**({"MX_RCNN_CHAOS": chaos_env}
                             if chaos_env else {}))
    return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _run_host0_inprocess(prefix, kv_dir, monkeypatch, *, resume=False,
                         flat=False, obs_dir=""):
    """Host 0 runs IN-PROCESS (so its returned params are directly
    comparable to the conftest baselines) while host 1 is a true
    subprocess."""
    monkeypatch.setenv("MXRCNN_SIM_PROCESS_ID", "0")
    monkeypatch.setenv("MXRCNN_SIM_NUM_PROCESSES", "2")
    return driver.run_fit(
        prefix, resume=resume, flat=flat, obs_dir=obs_dir,
        over_extra={"resilience.quorum_store_dir": kv_dir,
                    "resilience.quorum_timeout_s": 120.0})


def _coordinated_preemption(tmp_path, monkeypatch, flat, baseline):
    prefix = str(tmp_path / "run")
    obs0 = str(tmp_path / "obs")

    # leg A: host 1 (subprocess) is chaos-SIGTERM'd mid-epoch-1; host 0
    # (in-process) is never signaled but must drain and stop too.
    kv_a = str(tmp_path / "kv_a")
    proc1 = _spawn_host(1, 2, prefix, kv_a, flat=flat,
                        chaos_env="sigterm_at_step=4")
    with pytest.raises(PreemptionExit) as ei:
        _run_host0_inprocess(prefix, kv_a, monkeypatch, flat=flat,
                             obs_dir=obs0)
    assert ei.value.code == RESUMABLE_RC
    out1, _ = proc1.communicate(timeout=570)
    assert proc1.returncode == RESUMABLE_RC, (proc1.returncode, out1[-2000:])

    # exactly ONE consistent published state: latest_checkpoint agrees,
    # and its meta records the COMPLETE participating host set.
    found = latest_checkpoint(prefix)
    assert found is not None, os.listdir(prefix)
    meta = checkpoint_meta(prefix, *found)
    assert meta["host_count"] == 2 and meta["hosts"] == [0, 1], meta
    quorum_events = [e for e in report.load_events(obs0)
                     if e["type"] == "quorum"]
    assert any(e["kind"] == "preempt" and e["hosts"] == [0, 1]
               for e in quorum_events), quorum_events

    # leg B: dual --resume auto (fresh KV namespace — one dir per launch
    # attempt, the documented supervisor contract) -> bit-exact.
    kv_b = str(tmp_path / "kv_b")
    proc1 = _spawn_host(1, 2, prefix, kv_b, resume="auto", flat=flat)
    params_r = _run_host0_inprocess(prefix, kv_b, monkeypatch,
                                    resume="auto", flat=flat)
    out1, _ = proc1.communicate(timeout=570)
    assert proc1.returncode == 0, (proc1.returncode, out1[-2000:])
    _assert_trees_bitexact(baseline, params_r)


def _assert_trees_bitexact(a, b):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(b)}
    assert len(la) == len(lb)
    for path, va in la:
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(lb[jax.tree_util.keystr(path)]),
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_coordinated_preemption_two_hosts_tree(tmp_path, monkeypatch,
                                               tree_f32_baseline):
    _coordinated_preemption(tmp_path, monkeypatch, flat=False,
                            baseline=tree_f32_baseline)


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_coordinated_preemption_two_hosts_flat(tmp_path, monkeypatch,
                                               flat_f32_baseline):
    _coordinated_preemption(tmp_path, monkeypatch, flat=True,
                            baseline=flat_f32_baseline)


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_multihost_heal_excludes_straggler(tmp_path):
    """Both hosts lose their device at step 4 and heal; host 1 is
    chaos-armed to miss the heal rendezvous (H:site scoping). Host 0
    seals a one-host quorum (min_fraction 0.5 holds), finishes the run
    alone (rc 0) — its heal event carries the quorum outcome; host 1
    discovers the seal moved on without it and exits rc 75."""
    prefix = str(tmp_path / "run")
    kv = str(tmp_path / "kv")
    obs = str(tmp_path / "obs")
    chaos_env = ("device_lost_at_step=4 "
                 "barrier_timeout_at=1:quorum_barrier")
    procs = [_spawn_host(i, 2, prefix, kv, obs_dir=obs,
                         chaos_env=chaos_env, timeout_s=20)
             for i in range(2)]
    outs = [p.communicate(timeout=570)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0][-2000:]
    assert procs[1].returncode == RESUMABLE_RC, outs[1][-2000:]

    events = report.load_events(obs)  # folds both events_p<k>.jsonl
    (heal0,) = [e for e in events
                if e["type"] == "heal" and e["process"] == 0]
    assert heal0["quorum_hosts"] == [0]
    assert heal0["quorum_excluded"] == [1]
    assert heal0["quorum_spec"]  # survivors agreed a topology
    assert any(e["type"] == "quorum" and e["kind"] == "excluded"
               and e["process"] == 1 for e in events)
    summary = report.summarize(events)
    assert summary["quorum"]["excluded"] == [1]


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_elastic_grow_beyond_nominal_footprint(tmp_path, monkeypatch):
    """elastic_mode=grow: the run starts on a 2-wide mesh, loses a
    device, and the backend comes back with all 8 CPU devices — the
    healed session grows the data axis to 4 (the largest micro-batch
    divisor), beyond the nominal footprint."""
    monkeypatch.setenv(chaos.ENV_VAR, "device_lost_at_step=2")
    chaos.reset()
    prefix = str(tmp_path / "grown")
    obs = str(tmp_path / "obs")
    metrics = []
    driver.run_fit(prefix, mesh="2", num_images=8,
                   obs_dir=obs, epoch_metrics=metrics,
                   over_extra={"train.batch_images": 2,
                               "resilience.elastic_mode": "grow"})
    (ev,) = [e for e in report.load_events(obs) if e["type"] == "heal"]
    assert ev["devices_before"] == 2
    assert [e for e, _ in metrics] == [0, 1]  # completed both epochs
    # the rebuilt session's topology lands in the epoch save's sidecar
    meta = checkpoint_meta(prefix, 2, None)
    assert meta["mesh"] == {"data": 4, "model": 1}, meta


@pytest.mark.slow
@pytest.mark.compile_heavy
def test_elastic_rescale_too_deep_shrink(tmp_path, monkeypatch):
    """elastic_mode=rescale: 4-wide mesh shrinks to 3 devices — no
    divisor of the global batch, so the trainer keeps rows-per-device
    constant instead: loader rebuilt for 3 shards, images/dispatch drops
    4 -> 3 (visible in the epoch save's meta sidecar), LR schedule
    rebased, and the run completes without intervention."""
    monkeypatch.setenv(chaos.ENV_VAR,
                       "device_lost_at_step=2 shrink_on_reacquire=3")
    chaos.reset()
    prefix = str(tmp_path / "rescaled")
    obs = str(tmp_path / "obs")
    metrics = []
    driver.run_fit(prefix, mesh="4", num_images=8, obs_dir=obs,
                   epoch_metrics=metrics,
                   over_extra={"resilience.elastic_mode": "rescale"})
    (ev,) = [e for e in report.load_events(obs) if e["type"] == "heal"]
    assert ev["devices_before"] == 4 and ev["devices_after"] == 3
    assert [e for e, _ in metrics] == [0, 1]
    meta = checkpoint_meta(prefix, 2, None)
    assert meta["images_per_dispatch"] == 3, meta


# ---------------------------------------------------------------------------
# multi-host loud sync fallback + heal gate without a store (satellite c)
# ---------------------------------------------------------------------------

@pytest.mark.compile_heavy
def test_multihost_async_fallback_is_loud(tmp_path, monkeypatch, caplog):
    """Multi-host identity with NO reachable KV store: the async writer
    falls back to sync LOUDLY — one ``checkpoint`` event with
    fallback="sync" — and heal disables itself with a warning instead of
    wedging the fleet (coordination needs a store)."""
    monkeypatch.setenv("MXRCNN_SIM_PROCESS_ID", "0")
    monkeypatch.setenv("MXRCNN_SIM_NUM_PROCESSES", "2")
    obs = str(tmp_path / "obs")
    driver.run_fit(str(tmp_path / "run"), end_epoch=1, obs_dir=obs)
    falls = [e for e in report.load_events(obs)
             if e["type"] == "checkpoint" and e.get("fallback") == "sync"]
    assert len(falls) == 1 and "multi-host" in falls[0]["reason"]
    assert any("no KV store reachable" in r.message
               for r in caplog.records)
    assert any("heal disabled" in r.message for r in caplog.records)
