"""Proposal-recall grading + from-data bbox-target statistics.

Reference surface: rcnn/dataset/imdb.py::evaluate_recall (driven by
tools/test_rpn.py) and rcnn/processing/bbox_regression.py::
add_bbox_regression_targets (the BBOX_NORMALIZATION_PRECOMPUTED=False
branch).
"""

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.datasets import dataset_from_config
from mx_rcnn_tpu.targets.bbox_stats import (
    compute_bbox_stats,
    resolve_bbox_stats,
)


def _ds():
    cfg = generate_config("resnet50", "synthetic")
    return dataset_from_config(cfg.dataset)


def test_evaluate_recall_exact_counts():
    ds = _ds()
    roidb = [{
        "boxes": np.asarray([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32),
        "gt_classes": np.asarray([1, 2], np.int32),
    }]
    # Top-scored proposal covers gt2 only; second covers gt1.
    props = [np.asarray([[20, 20, 30, 30, 0.9], [0, 0, 10, 10, 0.8]],
                        np.float32)]
    r = ds.evaluate_recall(roidb, props, at=(1, 2))
    assert r["recall@1"] == pytest.approx(0.5)
    assert r["recall@2"] == pytest.approx(1.0)
    assert r["num_gt"] == 2.0 and r["num_proposals"] == 2.0


def test_evaluate_recall_resorts_by_score_column():
    ds = _ds()
    roidb = [{
        "boxes": np.asarray([[0, 0, 10, 10]], np.float32),
        "gt_classes": np.asarray([1], np.int32),
    }]
    # Mis-ordered dump: covering proposal carries the HIGHER score but
    # sits second — the score column must drive the top-N cut.
    props = [np.asarray([[50, 50, 60, 60, 0.2], [0, 0, 10, 10, 0.9]],
                        np.float32)]
    r = ds.evaluate_recall(roidb, props, at=(1,))
    assert r["recall@1"] == pytest.approx(1.0)


def test_evaluate_recall_greedy_one_to_one():
    """One proposal overlapping TWO clustered gts counts one covered gt
    (reference greedy matching removes the proposal after its first
    match), not two."""
    ds = _ds()
    roidb = [{
        # Two overlapping gts, both IoU >= 0.5 with the single proposal.
        "boxes": np.asarray([[0, 0, 99, 99], [0, 20, 99, 119]],
                            np.float32),
        "gt_classes": np.asarray([1, 1], np.int32),
    }]
    props = [np.asarray([[0, 10, 99, 109]], np.float32)]
    r = ds.evaluate_recall(roidb, props, at=(1,), iou_thresh=0.5)
    assert r["recall@1"] == pytest.approx(0.5)  # 1 of 2 gt covered


def test_evaluate_recall_iou_threshold():
    ds = _ds()
    roidb = [{
        "boxes": np.asarray([[0, 0, 99, 99]], np.float32),
        "gt_classes": np.asarray([1], np.int32),
    }]
    # Half-overlap proposal: IoU = 50x100 / (100x100 + 50x100 - 50x100)
    # = 0.5 (with +1 widths: just under/over depending on rounding) —
    # passes at 0.4, fails at 0.7.
    props = [np.asarray([[0, 0, 49, 99]], np.float32)]
    assert ds.evaluate_recall(roidb, props, at=(1,),
                              iou_thresh=0.4)["recall@1"] == 1.0
    assert ds.evaluate_recall(roidb, props, at=(1,),
                              iou_thresh=0.7)["recall@1"] == 0.0


def test_compute_bbox_stats_matches_manual_targets():
    gt = np.asarray([[10, 10, 50, 50]], np.float32)
    props = np.asarray([[12, 8, 54, 48], [8, 12, 46, 54]], np.float32)
    roidb = [{"boxes": gt, "gt_classes": np.asarray([1], np.int32),
              "proposals": props}]
    means, stds = compute_bbox_stats(roidb, fg_overlap=0.5)

    def t(ex, g):
        ew, eh = ex[2] - ex[0] + 1, ex[3] - ex[1] + 1
        gw, gh = g[2] - g[0] + 1, g[3] - g[1] + 1
        ecx, ecy = ex[0] + 0.5 * (ew - 1), ex[1] + 0.5 * (eh - 1)
        gcx, gcy = g[0] + 0.5 * (gw - 1), g[1] + 0.5 * (gh - 1)
        return np.asarray([(gcx - ecx) / ew, (gcy - ecy) / eh,
                           np.log(gw / ew), np.log(gh / eh)])

    targets = np.stack([t(p, gt[0]) for p in props])
    np.testing.assert_allclose(means, targets.mean(0), atol=1e-6)
    np.testing.assert_allclose(stds, targets.std(0), atol=1e-3)


def test_compute_bbox_stats_mirrors_flipped_entries():
    """A flip-doubled roidb (shared unflipped arrays + flipped=True) must
    measure the MIRRORED targets for the flipped copies: dx means cancel,
    matching the distribution training actually consumes."""
    gt = np.asarray([[10, 10, 50, 50]], np.float32)
    props = np.asarray([[18, 10, 58, 50], [16, 10, 56, 50]],
                       np.float32)  # pure +dx offsets
    base = {"boxes": gt, "gt_classes": np.asarray([1], np.int32),
            "proposals": props, "width": 100, "height": 60}
    flipped = dict(base, flipped=True)
    means_half, _ = compute_bbox_stats([base], fg_overlap=0.5)
    assert abs(means_half[0]) > 0.1  # unflipped alone: biased dx
    means, _ = compute_bbox_stats([base, flipped], fg_overlap=0.5)
    assert abs(means[0]) < 1e-6  # mirrored pair cancels dx
    assert abs(means[1] - means_half[1]) < 1e-6  # dy unaffected


def test_compute_bbox_stats_empty_falls_back():
    means, stds = compute_bbox_stats([], fg_overlap=0.5)
    assert means == (0.0, 0.0, 0.0, 0.0)
    assert stds == (0.1, 0.1, 0.2, 0.2)


def test_resolve_bbox_stats_precomputed_switch():
    cfg = generate_config("resnet50", "synthetic")
    gt = np.asarray([[10, 10, 60, 90]], np.float32)
    roidb = [{"boxes": gt, "gt_classes": np.asarray([1], np.int32),
              "proposals": np.asarray([[12, 12, 62, 88]], np.float32)}] * 4
    # Default: precomputed constants untouched.
    assert resolve_bbox_stats(cfg, roidb) is cfg
    # From-data branch: stats land in cfg.train (and thus flow into the
    # in-graph normalization and the checkpoint contract).
    from dataclasses import replace

    cfg2 = cfg.with_updates(train=replace(
        cfg.train, bbox_normalization_precomputed=False))
    out = resolve_bbox_stats(cfg2, roidb)
    assert out.train.bbox_means != cfg.train.bbox_means
    assert all(np.isfinite(out.train.bbox_means))
    assert all(s > 0 for s in out.train.bbox_stds)
