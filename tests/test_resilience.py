"""graftguard (mx_rcnn_tpu/resilience) gates — the round-5 postmortem as tests.

Every failure mode TPU_OUTAGE_r5.log / BENCH_r05 / VERDICT.md recorded is
injected here deterministically (resilience/chaos.py) and must be survived:

- classified backend acquisition: injected UNAVAILABLE xN -> the run
  proceeds after backoff with ``backend_retry`` events; a permanent error
  fails fast; the deadline bounds an endless outage.
- deadline-isolated benching: a hung config forfeits ONE row (a structured
  timeout row in partial.json), never the sweep (the rc=124 lesson).
- preemption-safe training: SIGTERM mid-epoch -> emergency checkpoint +
  resumable rc 75, and ``--resume auto`` reaches BIT-exact final params vs
  an uninterrupted run — in tree and flat (train.flat_params) modes, which
  also pins the PR 4 checkpoint-interchange claim under interruption.
- atomic checkpoints: SIGKILL inside the save's crash window leaves only a
  ``*.tmp-*`` dir no resume path ever considers.

All tests carry the ``chaos`` marker (script/smoke_resilience.sh runs just
this subset); they are tier-1 (NOT slow) — waiting for a real outage to
exercise recovery code is how round 5 happened.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import ResilienceConfig
from mx_rcnn_tpu.obs import open_event_log, report
from mx_rcnn_tpu.resilience import (
    RESUMABLE_RC,
    BackendUnavailableError,
    PreemptionExit,
    PreemptionGuard,
    acquire_backend,
    chaos,
    classify_backend_error,
)
from mx_rcnn_tpu.resilience.isolate import run_with_deadline
from mx_rcnn_tpu.train.checkpoint import (
    checkpoint_name,
    latest_checkpoint,
    latest_epoch,
    load_checkpoint,
)

import _resilience_driver as driver

pytestmark = pytest.mark.chaos

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO_ROOT, "tests", "_resilience_driver.py")

UNAVAILABLE_MSG = "UNAVAILABLE: TPU backend setup/compile error (Unavailable)."


def _subprocess_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("MX_RCNN_CHAOS", None)
    env.update(extra)
    return env


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    """No injection leaks between tests (or in from the outer env)."""
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# chaos spec parsing
# ---------------------------------------------------------------------------

def test_chaos_parse_roundtrip():
    spec = chaos.parse("backend_unavailable=3, sigterm_at_step=5 "
                       "hang_bench=c4_r101 hang_s=2.5 "
                       "die_at=checkpoint_finalize backend_permanent=true")
    assert spec.backend_unavailable == 3 and spec.sigterm_at_step == 5
    assert spec.hang_bench == "c4_r101" and spec.hang_s == 2.5
    assert spec.die_at == "checkpoint_finalize" and spec.backend_permanent
    assert spec.active


def test_chaos_unset_is_inert():
    spec = chaos.from_env(environ={})
    assert not spec.active
    # every hook is a no-op (site names must still be REGISTERED ones —
    # the chaos-site-name lint rule holds for tests too)
    spec.maybe_fail_backend()
    spec.maybe_sigterm(10_000)
    spec.maybe_hang("anything")
    spec.maybe_die("checkpoint_finalize")
    spec.maybe_device_loss(10_000)
    assert spec.maybe_shrink(["d0", "d1"]) == ["d0", "d1"]
    spec.fire("train_dispatch", step=10_000)
    assert chaos.site("backend_reacquire",
                      devices=["d0", "d1"]) == ["d0", "d1"]
    fire = spec.fire  # aliased: exercising the RUNTIME check, not lint
    with pytest.raises(ValueError, match="unregistered chaos site"):
        fire("not_a_site")


def test_chaos_rejects_unknown_key_and_bad_value():
    """A typo'd injection silently doing nothing would un-test the gate
    it was written for — parse must be loud."""
    with pytest.raises(ValueError, match="known keys"):
        chaos.parse("backend_unavailible=3")
    with pytest.raises(ValueError):
        chaos.parse("backend_unavailable=lots")
    with pytest.raises(ValueError, match="boolean"):
        chaos.parse("backend_permanent=treu")  # must not coerce to False
    assert not chaos.parse("backend_permanent=false").backend_permanent


# ---------------------------------------------------------------------------
# classified backend acquisition (acceptance gate a)
# ---------------------------------------------------------------------------

def test_classify_backend_error():
    assert classify_backend_error(RuntimeError(UNAVAILABLE_MSG)) == "transient"
    assert classify_backend_error(
        RuntimeError("DEADLINE_EXCEEDED: relay slow")) == "transient"
    assert classify_backend_error(
        RuntimeError("ABORTED: relay restarting")) == "transient"
    assert classify_backend_error(
        RuntimeError("INVALID_ARGUMENT: bad topology")) == "permanent"
    assert classify_backend_error(ValueError("nonsense")) == "permanent"


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_acquire_retries_transient_with_exponential_backoff(tmp_path):
    """UNAVAILABLE x3 -> backs off 2,4,8 (base 2, jitter 0), emits one
    backend_retry per failure + backend_up, and returns the devices —
    exactly what the round-5 watcher did by hand for 11 hours, minus the
    hand and the fixed cadence."""
    rcfg = ResilienceConfig(backend_deadline_s=1000.0,
                            backend_backoff_base_s=2.0,
                            backend_backoff_max_s=300.0,
                            backend_backoff_jitter=0.0)
    clock = _FakeClock()
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clock.t += s

    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise RuntimeError(UNAVAILABLE_MSG)
        return ["dev0", "dev1"]

    elog = open_event_log(str(tmp_path))
    devices = acquire_backend(rcfg, elog=elog, probe=probe, sleep=sleep,
                              clock=clock)
    elog.close()
    assert devices == ["dev0", "dev1"] and calls["n"] == 4
    assert sleeps == [2.0, 4.0, 8.0]

    events = report.load_events(str(tmp_path))
    retries = [e for e in events if e["type"] == "backend_retry"]
    ups = [e for e in events if e["type"] == "backend_up"]
    assert len(retries) == 3 and len(ups) == 1
    assert [r["attempt"] for r in retries] == [1, 2, 3]
    assert "UNAVAILABLE" in retries[-1]["error"]
    assert ups[0]["attempts"] == 4 and ups[0]["device_count"] == 2
    # the obs.report fold OUTAGES.md tells operators to read
    summary = report.summarize(events)
    assert summary["backend"]["retries"] == 3
    assert summary["backend"]["retry_wait_s"] == pytest.approx(14.0)
    assert "UNAVAILABLE" in summary["backend"]["last_error"]
    assert report.bench_blob(summary)["backend_retries"] == 3


def test_acquire_backoff_caps_and_respects_deadline():
    """An outage that outlasts backend_deadline_s raises
    BackendUnavailableError (chained to the last transient error), and no
    single sleep overshoots the deadline."""
    rcfg = ResilienceConfig(backend_deadline_s=10.0,
                            backend_backoff_base_s=4.0,
                            backend_backoff_max_s=8.0,
                            backend_backoff_jitter=0.0)
    clock = _FakeClock()
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clock.t += s

    def probe():
        raise RuntimeError(UNAVAILABLE_MSG)

    with pytest.raises(BackendUnavailableError, match="3 attempts") as ei:
        acquire_backend(rcfg, probe=probe, sleep=sleep, clock=clock)
    assert isinstance(ei.value.__cause__, RuntimeError)
    # 4, then min(cap 8, remaining 6): never sleeps past the deadline
    assert sleeps == [4.0, 6.0]


class _Dev:
    def __init__(self, platform):
        self.platform = platform


def test_acquire_detects_silent_platform_fallback(monkeypatch):
    """jax silently falls back to CPU when the relay is down — the probe
    then 'succeeds' instantly with the wrong device list. With
    resilience.backend_platform set, that fallback classifies as a
    transient outage (backend cache cleared so later probes can see the
    recovered relay) and retries until the expected platform appears."""
    from mx_rcnn_tpu.resilience import backend as backend_mod

    clears = []
    monkeypatch.setattr(backend_mod, "_clear_backend_cache",
                        lambda: clears.append(1))
    rcfg = ResilienceConfig(backend_platform="tpu",
                            backend_deadline_s=100.0,
                            backend_backoff_base_s=1.0,
                            backend_backoff_jitter=0.0)
    clock = _FakeClock()
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clock.t += s

    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] <= 2:
            return [_Dev("cpu")]  # the silent-fallback device list
        return [_Dev("tpu")]

    devices = acquire_backend(rcfg, probe=probe, sleep=sleep, clock=clock)
    assert [d.platform for d in devices] == ["tpu"] and calls["n"] == 3
    assert sleeps == [1.0, 2.0] and len(clears) == 2

    # an all-fallback outage still hits the deadline like any other
    rcfg = ResilienceConfig(backend_platform="tpu", backend_deadline_s=3.0,
                            backend_backoff_base_s=2.0,
                            backend_backoff_jitter=0.0)
    with pytest.raises(BackendUnavailableError) as ei:
        acquire_backend(rcfg, probe=lambda: [_Dev("cpu")], sleep=sleep,
                        clock=clock)
    assert "fell back" in str(ei.value.__cause__)
    # and unset (the default: CPU tests/dev boxes) accepts whatever came up
    devices = acquire_backend(ResilienceConfig(), probe=lambda: [_Dev("cpu")],
                              sleep=sleep, clock=clock)
    assert [d.platform for d in devices] == ["cpu"]


def test_acquire_permanent_fails_fast():
    """Retrying an INVALID_ARGUMENT for eleven hours is how a
    misconfigured run burns a deadline — the original error propagates
    on attempt 1 with zero sleeps."""
    rcfg = ResilienceConfig()
    sleeps = []

    def probe():
        raise RuntimeError("INVALID_ARGUMENT: bad topology")

    with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
        acquire_backend(rcfg, probe=probe, sleep=sleeps.append)
    assert sleeps == []


def test_acquire_through_chaos_env(monkeypatch, tmp_path):
    """The acceptance-gate wiring end to end: MX_RCNN_CHAOS arms the
    DEFAULT probe (the one train/eval/bench use), the injected outage
    rides through classified retry, and the run proceeds."""
    monkeypatch.setenv(chaos.ENV_VAR, "backend_unavailable=2")
    chaos.reset()
    rcfg = ResilienceConfig(backend_deadline_s=60.0,
                            backend_backoff_base_s=0.01,
                            backend_backoff_max_s=0.02)
    elog = open_event_log(str(tmp_path))
    devices = acquire_backend(rcfg, elog=elog, sleep=lambda s: None)
    elog.close()
    assert len(devices) >= 1  # the real (cpu) backend, post-outage
    events = report.load_events(str(tmp_path))
    assert sum(e["type"] == "backend_retry" for e in events) == 2
    assert sum(e["type"] == "backend_up" for e in events) == 1


def test_acquire_through_chaos_env_permanent(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR, "backend_permanent=1")
    chaos.reset()
    with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
        acquire_backend(ResilienceConfig(), sleep=lambda s: None)


# ---------------------------------------------------------------------------
# PreemptionGuard
# ---------------------------------------------------------------------------

def test_preemption_exit_carries_resumable_rc():
    assert RESUMABLE_RC == 75  # BSD EX_TEMPFAIL — the supervisor contract
    exc = PreemptionExit(signal.SIGTERM)
    assert isinstance(exc, SystemExit) and exc.code == RESUMABLE_RC
    assert exc.signum == signal.SIGTERM


def test_guard_records_real_sigterm_and_restores_handlers():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    guard = PreemptionGuard()
    with guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(200):  # delivery is near-immediate in-thread
            if guard.requested:
                break
            time.sleep(0.005)
        assert guard.requested and guard.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev_term
    assert signal.getsignal(signal.SIGINT) is prev_int


def test_guard_second_sigint_is_immediate():
    """The first Ctrl-C asks for an orderly save; the second means NOW."""
    guard = PreemptionGuard()
    guard._handle(signal.SIGINT, None)
    assert guard.requested and guard.signum == signal.SIGINT
    with pytest.raises(KeyboardInterrupt):
        guard._handle(signal.SIGINT, None)


def test_guard_inert_off_main_thread():
    results = []
    t = threading.Thread(target=lambda: results.append(
        PreemptionGuard().install()))
    t.start()
    t.join()
    assert results == [False]


# ---------------------------------------------------------------------------
# checkpoint name grammar / resume-point discovery
# ---------------------------------------------------------------------------

def test_checkpoint_name_grammar_and_ordering(tmp_path):
    assert checkpoint_name(7) == "0007"
    assert checkpoint_name(3, 12) == "0003d00012"
    for d in ("0001", "0001d00003", "0000d00005", "0002.tmp-123",
              "checkpoint_junk"):
        (tmp_path / d).mkdir()
    # emergency (1,3) outranks boundary (1,-) == (1,0); tmp/junk invisible
    assert latest_checkpoint(str(tmp_path)) == (1, 3)
    # the pre-graftguard contract ignores emergency saves entirely
    assert latest_epoch(str(tmp_path)) == 1
    (tmp_path / "0002").mkdir()
    assert latest_checkpoint(str(tmp_path)) == (2, None)
    assert latest_epoch(str(tmp_path)) == 2


def test_latest_checkpoint_empty(tmp_path):
    assert latest_checkpoint(str(tmp_path)) is None
    assert latest_checkpoint(str(tmp_path / "never_made")) is None


# ---------------------------------------------------------------------------
# deadline isolation (acceptance gate b)
# ---------------------------------------------------------------------------

def test_run_with_deadline_returns_child_row():
    row = run_with_deadline(driver.sweep_runner, "cfg_a", timeout_s=60.0,
                            label="cfg_a")
    assert row == {"img_s_per_chip": 1.0, "which": "cfg_a"}


def test_run_with_deadline_kills_hung_child():
    t0 = time.monotonic()
    row = run_with_deadline(driver.sleepy_runner, "hung", timeout_s=3.0,
                            label="hung")
    assert row["timeout_s"] == 3.0 and "deadline" in row["error"]
    assert time.monotonic() - t0 < 30.0  # killed, not waited out


def test_run_with_deadline_reports_child_error():
    row = run_with_deadline(driver.error_runner, "boom", timeout_s=60.0,
                            label="boom")
    assert row == {"error": "RuntimeError: relay dropped mid-measure (boom)"}


def test_sweep_survives_injected_hang(monkeypatch, tmp_path):
    """THE BENCH_r05 gate: chaos hangs config "b" past its deadline; the
    sweep records a structured timeout row for it and still completes
    "a" and "c", all three durable in partial.json."""
    import bench

    monkeypatch.setenv(chaos.ENV_VAR, "hang_bench=b hang_s=120")
    flush = str(tmp_path / "partial.json")
    detail = bench.run_sweep({"a": "a", "b": "b", "c": "c"},
                             driver.sweep_runner, flush_path=flush,
                             timeout_s=8.0)
    assert detail["a"] == {"img_s_per_chip": 1.0, "which": "a"}
    assert detail["c"] == {"img_s_per_chip": 1.0, "which": "c"}
    assert detail["b"]["timeout_s"] == 8.0 and "error" in detail["b"]
    with open(flush, encoding="utf-8") as fh:
        on_disk = json.load(fh)
    assert set(on_disk) == {"a", "b", "c"}
    assert on_disk["b"]["timeout_s"] == 8.0


# ---------------------------------------------------------------------------
# atomic checkpoint publication (satellite: crash-window test)
# ---------------------------------------------------------------------------

def test_checkpoint_crash_window_leaves_nothing_resumable(tmp_path):
    """SIGKILL between the full orbax write and the publishing rename
    (chaos site ``checkpoint_finalize``): the prefix holds only a
    ``*.tmp-*`` dir, which NO resume path considers — then a clean save
    of the same tree publishes and loads."""
    prefix = str(tmp_path / "ck")
    proc = subprocess.run(
        [sys.executable, DRIVER, "--crash-save", prefix],
        env=_subprocess_env(MX_RCNN_CHAOS="die_at=checkpoint_finalize"),
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=240)
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    leftovers = os.listdir(prefix)
    assert leftovers and all(".tmp-" in d for d in leftovers), leftovers
    assert latest_epoch(prefix) is None
    assert latest_checkpoint(prefix) is None

    proc = subprocess.run(
        [sys.executable, DRIVER, "--crash-save", prefix],
        env=_subprocess_env(), cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    assert latest_epoch(prefix) == 1
    # the clean save also swept the dead process's abandoned tmp dir
    assert not any(".tmp-" in d for d in os.listdir(prefix))
    expect = np.arange(6, dtype=np.float32).reshape(2, 3)
    loaded, _ = load_checkpoint(prefix, 1,
                                template={"w": np.zeros_like(expect)})
    np.testing.assert_array_equal(loaded["w"], expect)


def test_checkpoint_resave_crash_preserves_previous_good(tmp_path):
    """A re-save of an EXISTING checkpoint dir must never destroy the
    previous good copy before the new one is published: SIGKILL at the
    ``checkpoint_swap`` site (old set aside, new not yet renamed in)
    leaves the old data recoverable at ``<name>.old`` — never a window
    where an rmtree'd checkpoint is simply gone — and the next clean
    save publishes and cleans up every leftover."""
    prefix = str(tmp_path / "ck")
    proc = subprocess.run(
        [sys.executable, DRIVER, "--crash-save", prefix],
        env=_subprocess_env(), cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr

    proc = subprocess.run(
        [sys.executable, DRIVER, "--crash-save", prefix, "--scale", "3"],
        env=_subprocess_env(MX_RCNN_CHAOS="die_at=checkpoint_swap"),
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=240)
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    names = os.listdir(prefix)
    expect = np.arange(6, dtype=np.float32).reshape(2, 3)
    # the old data survived the crash (outside the resume name grammar)
    assert "0001.old" in names and "0001" not in names, names
    assert latest_checkpoint(prefix) is None

    proc = subprocess.run(
        [sys.executable, DRIVER, "--crash-save", prefix, "--scale", "3"],
        env=_subprocess_env(), cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    assert latest_epoch(prefix) == 1
    assert sorted(os.listdir(prefix)) == ["0001"]  # aside + tmps cleaned
    loaded, _ = load_checkpoint(prefix, 1,
                                template={"w": np.zeros_like(expect)})
    np.testing.assert_array_equal(loaded["w"], 3 * expect)


# ---------------------------------------------------------------------------
# preemption-safe training (acceptance gate c): kill -> resume parity
# ---------------------------------------------------------------------------

def _assert_trees_bitexact(a, b):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v
          for p, v in jax.tree_util.tree_leaves_with_path(b)}
    assert len(la) == len(lb)
    for path, va in la:
        np.testing.assert_array_equal(np.asarray(va),
                                      np.asarray(lb[jax.tree_util.keystr(path)]),
                                      err_msg=jax.tree_util.keystr(path))


def _parity(tmp_path, monkeypatch, flat, compute="f32", params_u=None):
    """SIGTERM at global step 4 (mid-epoch 1 of 2x3) -> PreemptionExit
    rc 75 with a dispatch-tagged emergency save and a `preempt` event;
    --resume auto then reaches params BIT-exact vs uninterrupted
    (``params_u`` supplies a precomputed uninterrupted baseline — the
    session-scope bf16 one is shared with test_heal.py)."""
    if params_u is None:
        params_u = driver.run_fit(str(tmp_path / "uninterrupted"),
                                  flat=flat, compute=compute)

    monkeypatch.setenv(chaos.ENV_VAR, "sigterm_at_step=4")
    chaos.reset()
    obs_dir = str(tmp_path / "obs_interrupted")
    with pytest.raises(PreemptionExit) as ei:
        driver.run_fit(str(tmp_path / "killed"), flat=flat, obs_dir=obs_dir,
                       compute=compute)
    assert ei.value.code == RESUMABLE_RC
    assert latest_checkpoint(str(tmp_path / "killed")) == (1, 1)
    assert os.path.isdir(tmp_path / "killed" / "0001d00001")
    preempts = [e for e in report.load_events(obs_dir)
                if e["type"] == "preempt"]
    assert len(preempts) == 1 and preempts[0]["step"] == 4
    assert preempts[0]["saved"].endswith("0001d00001")

    monkeypatch.delenv(chaos.ENV_VAR)
    chaos.reset()
    obs_resumed = str(tmp_path / "obs_resumed")
    params_r = driver.run_fit(str(tmp_path / "killed"), flat=flat,
                              resume="auto", obs_dir=obs_resumed,
                              compute=compute)
    _assert_trees_bitexact(params_u, params_r)
    # telemetry indices CONTINUE at the skip point (dispatch 1 of the
    # interrupted epoch) — no double-use of batch numbers the
    # pre-preemption run already logged/emitted.
    resumed_e1 = sorted(e["batch"] for e in report.load_events(obs_resumed)
                        if e["type"] == "step" and e["epoch"] == 1)
    assert resumed_e1 == [1, 2], resumed_e1


@pytest.mark.compile_heavy
def test_kill_resume_parity_tree(tmp_path, monkeypatch, tree_f32_baseline):
    _parity(tmp_path, monkeypatch, flat=False, params_u=tree_f32_baseline)


@pytest.mark.compile_heavy
def test_kill_resume_parity_flat(tmp_path, monkeypatch, flat_f32_baseline):
    """The PR 4 checkpoint-interchange claim under interruption: the
    emergency save is TREE-form even from flat buffers, and the resumed
    flat run still matches uninterrupted bit for bit."""
    _parity(tmp_path, monkeypatch, flat=True, params_u=flat_f32_baseline)


@pytest.mark.compile_heavy
def test_kill_resume_parity_bf16(tmp_path, monkeypatch, bf16_flat_baseline):
    """graftcast under interruption: compute_dtype=bf16 + flat — the
    emergency save is f32 TREE-form (masters only; the compute shadow is
    derived state), the resumed session re-cuts buffers AND re-derives
    the shadow from the restored masters, and the whole thing is still
    bit-exact vs an uninterrupted bf16 run (bf16 rounding is
    deterministic on a fixed backend)."""
    _parity(tmp_path, monkeypatch, flat=True, compute="bf16",
            params_u=bf16_flat_baseline)


@pytest.mark.compile_heavy
def test_preemption_rc_subprocess(tmp_path):
    """The process-level contract a supervisor sees: chaos SIGTERM at
    step 2 -> the driver exits rc 75 (not a crash, not rc 0), leaving a
    resumable emergency checkpoint behind."""
    prefix = str(tmp_path / "run")
    proc = subprocess.run(
        [sys.executable, DRIVER, "--fit", prefix, "--end-epoch", "2"],
        env=_subprocess_env(MX_RCNN_CHAOS="sigterm_at_step=2"),
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=570)
    assert proc.returncode == RESUMABLE_RC, (proc.returncode, proc.stderr)
    found = latest_checkpoint(prefix)
    assert found is not None and found[1] is not None, found
