"""Ring attention (sequence-parallel) vs dense softmax attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention,
    ring_attention_sharded,
)
from mx_rcnn_tpu.parallel.mesh import create_mesh


def _qkv(rng, b=2, s=32, h=4, d=8):
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, h, d).astype(np.float32)
    v = rng.randn(b, s, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_single_block_equals_dense(rng):
    """Ring of size 1 degenerates to dense attention exactly."""
    q, k, v = _qkv(rng)
    mesh = create_mesh("1")
    out = ring_attention(q, k, v, mesh, axis="data")
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_ring_matches_dense(rng, ring):
    if jax.device_count() < ring:
        pytest.skip(f"needs {ring} devices")
    q, k, v = _qkv(rng, s=8 * ring)
    mesh = create_mesh(str(ring))
    out = ring_attention(q, k, v, mesh, axis="data")
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_under_jit(rng):
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    q, k, v = _qkv(rng, s=32)
    mesh = create_mesh("4")
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(q, k, v)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_extreme_logits_stable(rng):
    """Streaming softmax must survive large-magnitude scores."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    q, k, v = _qkv(rng, s=16, d=4)
    q = q * 30.0  # logits ~ hundreds
    mesh = create_mesh("4")
    out = ring_attention(q, k, v, mesh)
    want = dense_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_bf16_io(rng):
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    q, k, v = _qkv(rng, s=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = create_mesh("2")
    out = ring_attention(qb, kb, vb, mesh)
    assert out.dtype == jnp.bfloat16
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_ring_dp_sp_batch_sharded(rng):
    """DP x SP layout: ring over 'model' with the batch sharded over
    'data' (the ViTDet use_ring_attention layout). Regression: the
    fori_loop carry must be marked varying over BOTH axes, and a batch
    not divisible by the data axis (init_vitdet_params' batch-1 dummy)
    must fall back to a replicated batch instead of failing device_put."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = create_mesh("4x2")
    q, k, v = _qkv(rng, b=4, s=16)
    out = ring_attention(q, k, v, mesh, axis="model")
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # batch 1 (not divisible by data=4): replicated-batch fallback.
    q1, k1, v1 = _qkv(rng, b=1, s=16)
    out1 = ring_attention(q1, k1, v1, mesh, axis="model")
    np.testing.assert_allclose(np.asarray(out1),
                               np.asarray(dense_attention(q1, k1, v1)),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_matches_dense(rng):
    """All-to-all SP formulation == dense attention (heads divisible)."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.ops.ring_attention import ulysses_attention
    q, k, v = _qkv(rng, b=2, s=32, h=4, d=8)
    mesh = create_mesh("4")
    out = ulysses_attention(q, k, v, mesh, axis="data")
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_dp_sp_and_grad(rng):
    """Ulysses under the DP x SP (4x2) layout, and its gradient flows."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from mx_rcnn_tpu.ops.ring_attention import ulysses_attention
    mesh = create_mesh("4x2")
    q, k, v = _qkv(rng, b=4, s=16, h=4, d=8)
    out = ulysses_attention(q, k, v, mesh, axis="model")
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c) ** 2)

    g_sp = jax.grad(loss(lambda a, b, c: ulysses_attention(
        a, b, c, mesh, axis="model")))(q, k, v)
    g_dense = jax.grad(loss(dense_attention))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_sp), np.asarray(g_dense),
                               rtol=5e-4, atol=5e-4)


def test_ulysses_head_divisibility_error(rng):
    """heads not divisible by the SP axis -> clear error, not garbage."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.ops.ring_attention import ulysses_attention
    q, k, v = _qkv(rng, b=1, s=16, h=3, d=8)
    mesh = create_mesh("4")
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh, axis="data")


def test_streaming_attention_matches_dense(rng):
    """Flash-style streaming softmax (the Ulysses local attention) ==
    dense, exercised with >1 key chunk."""
    from mx_rcnn_tpu.ops.ring_attention import streaming_attention
    q, k, v = _qkv(rng, b=2, s=256, h=2, d=8)
    out = streaming_attention(q, k, v, kv_chunk=64)  # 4 chunks
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # auto chunk selection at a chunking length (2048 -> 2x1024).
    q2, k2, v2 = _qkv(rng, b=1, s=2048, h=1, d=4)
    out2 = streaming_attention(q2, k2, v2)
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(dense_attention(q2, k2, v2)),
                               rtol=2e-5, atol=2e-5)
    # non-divisible length: padded tail chunk, masked keys (s=300 with
    # chunk 128 -> 3 chunks, 84 padded keys).
    q3, k3, v3 = _qkv(rng, b=1, s=300, h=2, d=4)
    out3 = streaming_attention(q3, k3, v3, kv_chunk=128)
    np.testing.assert_allclose(np.asarray(out3),
                               np.asarray(dense_attention(q3, k3, v3)),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_streaming_under_sp(rng):
    """The chunked streaming path INSIDE shard_map: small kv_chunk forces
    >1 key block (incl. a padded tail) under the SP re-partition."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from mx_rcnn_tpu.ops.ring_attention import ulysses_attention
    mesh = create_mesh("4")
    q, k, v = _qkv(rng, b=2, s=48, h=4, d=8)  # S_full=48, 16/chunk -> 3
    out = ulysses_attention(q, k, v, mesh, axis="data", kv_chunk=16)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # padded tail inside shard_map: S_full=40, chunk 16 -> 3 chunks, 8 pad.
    q2, k2, v2 = _qkv(rng, b=1, s=40, h=4, d=8)
    out2 = ulysses_attention(q2, k2, v2, mesh, axis="data", kv_chunk=16)
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(dense_attention(q2, k2, v2)),
                               rtol=2e-5, atol=2e-5)
