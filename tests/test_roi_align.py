"""ROIAlign / ROIPool vs numpy references and invariants."""

import numpy as np
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.roi_align import roi_align, roi_pool


def test_roi_align_constant_map():
    # Pooling a constant feature map must return the constant.
    feat = jnp.full((1, 16, 16, 3), 2.5)
    rois = jnp.array([[0.0, 8.0, 8.0, 120.0, 120.0]])
    out = roi_align(feat, rois, output_size=7, spatial_scale=1.0 / 16.0)
    assert out.shape == (1, 7, 7, 3)
    assert np.allclose(out, 2.5, atol=1e-5)


def test_roi_align_linear_ramp():
    # f(x,y) = x is reproduced exactly by bilinear sampling + averaging.
    w = 32
    ramp = jnp.tile(jnp.arange(w, dtype=jnp.float32)[None, :, None], (w, 1, 1))
    feat = ramp[None]  # (1, 32, 32, 1)
    # roi covering feature cols [4, 28] at scale 1 (image == feature coords).
    rois = jnp.array([[0.0, 4.0, 4.0, 28.0, 28.0]])
    out = roi_align(feat, rois, output_size=4, spatial_scale=1.0, sampling_ratio=2)
    # bin width = 24/4 = 6; bin k spans x in [4+6k, 4+6k+6); mean sample x
    # = 4 + 6k + 3 = centre of the bin.
    want = np.array([7.0, 13.0, 19.0, 25.0])
    assert np.allclose(np.asarray(out)[0, 2, :, 0], want, atol=1e-4)


def test_roi_align_batch_index():
    feat = jnp.stack([jnp.zeros((8, 8, 1)), jnp.ones((8, 8, 1))])  # (2,8,8,1)
    rois = jnp.array([[0.0, 0.0, 0.0, 7.0, 7.0], [1.0, 0.0, 0.0, 7.0, 7.0]])
    out = roi_align(feat, rois, output_size=2, spatial_scale=1.0)
    assert np.allclose(out[0], 0.0)
    assert np.allclose(out[1], 1.0)


def test_roi_pool_max_semantics():
    # Single hot pixel: max pool must find it in the covering bin.
    feat = np.zeros((1, 8, 8, 1), np.float32)
    feat[0, 5, 6, 0] = 9.0
    rois = jnp.array([[0.0, 0.0, 0.0, 7.0, 7.0]])
    out = np.asarray(roi_pool(jnp.array(feat), rois, output_size=2, spatial_scale=1.0))
    # Bin (1,1) covers rows/cols [4,8): contains (5,6).
    assert out[0, 1, 1, 0] == 9.0
    assert out[0, 0, 0, 0] == 0.0


def test_roi_pool_scale_quantization():
    # spatial_scale 1/16: image box (0,0,31,31) -> feature box (0,0,2,2).
    feat = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    rois = jnp.array([[0.0, 0.0, 0.0, 31.0, 31.0]])
    out = np.asarray(
        roi_pool(jnp.array(feat), rois, output_size=1, spatial_scale=1.0 / 16.0)
    )
    # max over rows/cols 0..2 = feat[2,2] = 10.
    assert out[0, 0, 0, 0] == 10.0


def test_jit_and_grad():
    feat = jnp.ones((1, 8, 8, 2))
    rois = jnp.array([[0.0, 2.0, 2.0, 6.0, 6.0]])

    def f(x):
        return roi_align(x, rois, output_size=2, spatial_scale=1.0).sum()

    g = jax.grad(f)(feat)
    assert g.shape == feat.shape
    # Gradient mass = number of pooled outputs (mean weights sum to 1/bin).
    assert np.isclose(float(g.sum()), 2 * 2 * 2, atol=1e-4)


def test_roi_align_matmul_matches_gather_oracle():
    """The MXU matmul formulation == the per-point bilinear gather oracle."""
    from mx_rcnn_tpu.ops.roi_align import roi_align_gather

    rs = np.random.RandomState(3)
    feat = jnp.asarray(rs.randn(2, 12, 10, 5).astype(np.float32))
    rois = jnp.asarray(
        [
            [0.0, 5.0, 3.0, 90.0, 100.0],
            [1.0, 0.0, 0.0, 159.0, 191.0],
            [0.0, 30.0, 40.0, 32.0, 44.0],   # tiny box (sub-bin)
            [1.0, -10.0, -10.0, 200.0, 300.0],  # out-of-bounds corners
        ],
        jnp.float32,
    )
    for aligned in (False, True):
        for sr in (1, 2):
            a = roi_align(feat, rois, 7, 1.0 / 16.0, sampling_ratio=sr,
                          aligned=aligned)
            b = roi_align_gather(feat, rois, 7, 1.0 / 16.0, sampling_ratio=sr,
                                 aligned=aligned)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


def test_roi_align_matmul_grad_matches_gather_oracle():
    from mx_rcnn_tpu.ops.roi_align import roi_align_gather

    rs = np.random.RandomState(4)
    feat = jnp.asarray(rs.randn(1, 8, 8, 3).astype(np.float32))
    rois = jnp.asarray([[0.0, 10.0, 6.0, 100.0, 90.0]], jnp.float32)

    g1 = jax.grad(lambda x: roi_align(x, rois, 4, 1 / 16).sum())(feat)
    g2 = jax.grad(lambda x: roi_align_gather(x, rois, 4, 1 / 16).sum())(feat)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4,
                               atol=1e-5)
