"""Alternate-training stage drivers (tools/stages.py, train_alternate.py).

Reference: train_alternate.py's 7-step Ren et al. pipeline chained from
rcnn/tools/{train_rpn,test_rpn,train_rcnn,test_rcnn,reeval}.py (SURVEY.md
§4.4) — stages communicate via files (checkpoints + proposal pickles).
These tests execute the real file contracts at tiny shapes: the slow gate
runs the WHOLE 7-step dance; the fast tests pin the proposal-pickle →
rpn_roidb → ROIIter leg and reeval without training.
"""

import pickle

import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.datasets import dataset_from_config
from mx_rcnn_tpu.data.loader import ROIIter, TestLoader
from mx_rcnn_tpu.evaluation.tester import Predictor, generate_proposals
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.tools import stages

TINY = {
    "dataset.kwargs": (("num_images", 6), ("image_size", 128),
                       ("max_objects", 2), ("min_size_frac", 4),
                       ("max_size_frac", 2)),
    "image.pad_shape": (128, 128),
    "image.scales": ((128, 128),),
    "network.norm": "group",
    "network.freeze_at": 0,
    "network.anchor_scales": (2, 4, 8),
    "train.rpn_positive_overlap": 0.5,
    "train.rpn_pre_nms_top_n": 256,
    "train.rpn_post_nms_top_n": 64,
    "train.batch_rois": 32,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "train.flip": False,
    "train.lr": 0.001,
    "train.lr_step": (100,),
    "test.rpn_pre_nms_top_n": 128,
    "test.rpn_post_nms_top_n": 32,
    "test.max_per_image": 8,
}


def tiny_cfg():
    return generate_config("resnet50", "synthetic", **TINY)


def test_generate_then_roiiter_contract(tmp_path):
    """Stage 2→3 file contract without training: RPN proposal dump from a
    fresh-init predictor → rpn_roidb merge → ROIIter batches carry scaled,
    valid proposals (reference: test_rpn.py --gen → train_rcnn.py)."""
    cfg = tiny_cfg()
    params = zoo.init_params(zoo.build_model(cfg), cfg, jax.random.PRNGKey(0))

    rpn_file = str(tmp_path / "props.pkl")
    files, recalls = stages.test_rpn_generate(cfg, params, rpn_file)
    assert files == [rpn_file]
    # Recall grading runs alongside the dump (reference: test_rpn.py →
    # imdb.evaluate_recall). Fresh-init RPN → any finite value in [0, 1].
    assert len(recalls) == 1
    for n in (300, 1000, 2000):
        assert 0.0 <= recalls[0][f"recall@{n}"] <= 1.0
    assert recalls[0]["num_gt"] > 0

    with open(rpn_file, "rb") as f:
        dumped = pickle.load(f)
    ds = dataset_from_config(cfg.dataset)
    assert len(dumped) == len(ds.gt_roidb())
    for arr in dumped:
        assert arr.ndim == 2 and arr.shape[1] == 5  # x1y1x2y2 + score
        assert np.isfinite(arr).all()

    roidb = stages._attach_proposals(cfg, rpn_file)
    assert roidb and all("proposals" in r for r in roidb)

    it = ROIIter(roidb, cfg, num_shards=1, max_proposals=64, seed=0)
    batch = next(iter(it))
    assert batch["proposals"].shape == (1, 64, 4)
    assert batch["proposal_valid"].any()
    # proposals are image-scale pixels inside the padded canvas
    v = batch["proposals"][batch["proposal_valid"]]
    assert (v[:, 2] >= v[:, 0]).all() and (v[:, 3] >= v[:, 1]).all()
    assert v.max() <= max(cfg.image.pad_shape)


def test_reeval_roundtrip(tmp_path):
    """tools/reeval.py analog: saved all_boxes pickle → evaluate_detections
    (reference: re-scoring saved detections without a model)."""
    cfg = tiny_cfg()
    ds = dataset_from_config(cfg.dataset, cfg.dataset.test_image_set)
    roidb = ds.gt_roidb()
    num_images = len(roidb)
    # Perfect detections: each gt box at score 1 in its own class slot.
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(num_images)]
                 for _ in range(ds.num_classes)]
    for i, entry in enumerate(roidb):
        for box, cls in zip(entry["boxes"], entry["gt_classes"]):
            det = np.concatenate([box, [1.0]]).astype(np.float32)[None]
            all_boxes[cls][i] = np.concatenate([all_boxes[cls][i], det])
    pkl = str(tmp_path / "detections.pkl")
    with open(pkl, "wb") as f:
        pickle.dump(all_boxes, f)
    result = stages.reeval(ds, pkl)
    assert result["mAP"] > 0.99, result


def test_selective_search_proposal_roidb_trains(tmp_path):
    """Fast R-CNN over EXTERNAL proposals (reference: selective-search
    pickles via rcnn/utils/load_data.py::load_proposal_roidb): a (n,4)/(n,5)
    per-image pickle → load_proposal_roidb → ROIIter → one finite
    forward_train_rcnn grad step, no RPN anywhere."""
    from mx_rcnn_tpu.models.faster_rcnn import forward_train_rcnn

    cfg = tiny_cfg()
    ds = dataset_from_config(cfg.dataset)
    gt = ds.gt_roidb()

    rs = np.random.RandomState(0)
    props = []
    for i, entry in enumerate(gt):
        jit = entry["boxes"].astype(np.float32) + rs.uniform(-4, 4, (len(entry["boxes"]), 4))
        rand = rs.uniform(0, 100, (16, 4)).astype(np.float32)
        rand[:, 2:] = rand[:, :2] + rs.uniform(8, 28, (16, 2))
        arr = np.concatenate([jit, rand]).astype(np.float32)
        if i % 2:  # exercise both accepted layouts
            arr = np.concatenate([arr, rs.rand(len(arr), 1).astype(np.float32)], axis=1)
        props.append(arr)
    pkl = str(tmp_path / "ss_proposals.pkl")
    with open(pkl, "wb") as f:
        pickle.dump(props, f)

    roidb = ds.load_proposal_roidb(gt, pkl)
    assert all(r["proposals"].shape[1] == 4 for r in roidb)

    it = ROIIter(roidb, cfg, num_shards=1, max_proposals=32, seed=0)
    batch = next(iter(it))
    assert batch["proposal_valid"].any()

    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch_j = {k: jnp.asarray(v) for k, v in batch.items()}
    (loss, _), grads = jax.jit(jax.value_and_grad(
        lambda p: forward_train_rcnn(model, p, batch_j,
                                     jax.random.PRNGKey(1), cfg),
        has_aux=True))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree_util.tree_leaves(grads))


@pytest.mark.slow
def test_alternate_training_full_pipeline(tmp_path):
    """The 7-step dance end-to-end at tiny shapes: every stage driver in
    tools/stages.py runs for real, files are the only coupling, and the
    combined checkpoint evaluates through test_rcnn (tools/test_rcnn.py
    analog). One epoch per stage: this gates plumbing, not convergence."""
    from mx_rcnn_tpu.train.checkpoint import load_checkpoint
    from train_alternate import alternate_train

    cfg = tiny_cfg()
    prefix = str(tmp_path / "alt")
    final = alternate_train(cfg, prefix, rpn_epoch=1, rcnn_epoch=1,
                            frequent=1000)

    # stage artifacts all exist
    import os
    for f in ("_rpn1_proposals.pkl", "_rpn2_proposals.pkl"):
        assert os.path.exists(prefix + f), f
    for d in ("_rpn1", "_rcnn1", "_rpn2", "_rcnn2"):
        assert os.path.isdir(prefix + d), d

    # stage 4/6 froze the trunk: rpn2/rcnn2 share stage-3's features
    t_rcnn1 = zoo.init_params(zoo.build_model(cfg), cfg,
                              jax.random.PRNGKey(0))
    rcnn1, _ = load_checkpoint(prefix + "_rcnn1", 1,
                               template={"params": t_rcnn1},
                               means=cfg.train.bbox_means,
                               stds=cfg.train.bbox_stds,
                               num_classes=cfg.dataset.num_classes)
    f_final = final["params"]["features"]
    f_rcnn1 = rcnn1["params"]["features"]
    leaf = lambda t: np.asarray(
        t["stage3"]["block0"]["conv1"]["kernel"])  # noqa: E731
    np.testing.assert_array_equal(leaf(f_final), leaf(f_rcnn1))

    # combined checkpoint: saved at epoch 0 and eval-able (test_rcnn)
    result = stages.test_rcnn(cfg, prefix, 0)
    assert "mAP" in result and np.isfinite(result["mAP"])


def test_bg_thresh_lo_sentinel_preset():
    """train.bg_thresh_lo=None (the unset sentinel) gets the reference's
    Fast-RCNN 0.1 preset on the alternate path, while an explicit value —
    INCLUDING 0.0, which the sentinel makes expressible — is respected
    (advisor r5: an explicit 0.0 used to be silently overwritten)."""
    from dataclasses import replace

    cfg = tiny_cfg()
    assert cfg.train.bg_thresh_lo is None
    assert cfg.train.bg_thresh_lo_value == 0.0  # end2end resolution
    assert stages.apply_fast_rcnn_bg_preset(cfg).train.bg_thresh_lo == 0.1

    explicit_zero = cfg.with_updates(
        train=replace(cfg.train, bg_thresh_lo=0.0))
    kept = stages.apply_fast_rcnn_bg_preset(explicit_zero)
    assert kept.train.bg_thresh_lo == 0.0
    assert kept.train.bg_thresh_lo_value == 0.0

    explicit = cfg.with_updates(train=replace(cfg.train, bg_thresh_lo=0.2))
    assert stages.apply_fast_rcnn_bg_preset(explicit).train.bg_thresh_lo == 0.2
