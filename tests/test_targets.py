"""Tests for assign_anchor / sample_rois vs reference semantics."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.anchors import anchor_grid
from mx_rcnn_tpu.targets.rpn_targets import assign_anchor
from mx_rcnn_tpu.targets.rcnn_targets import sample_rois


def pad_gt(boxes, g=8):
    out = np.zeros((g, 4), np.float32)
    valid = np.zeros((g,), bool)
    if len(boxes):
        out[: len(boxes)] = boxes
        valid[: len(boxes)] = True
    return jnp.array(out), jnp.array(valid)


class TestAssignAnchor:
    def setup_method(self):
        self.anchors = jnp.array(anchor_grid(16, 16, stride=16))
        self.im_info = jnp.array([256.0, 256.0, 1.0])
        self.key = jax.random.PRNGKey(0)

    def test_positive_on_exact_match(self):
        # gt equal to one anchor -> that anchor must be labeled 1.
        a = np.asarray(self.anchors)
        inside = (a[:, 0] >= 0) & (a[:, 1] >= 0) & (a[:, 2] < 256) & (a[:, 3] < 256)
        idx = int(np.nonzero(inside)[0][0])
        gt, gtv = pad_gt([a[idx]])
        t = assign_anchor(self.anchors, gt, gtv, self.im_info, self.key)
        assert int(t.labels[idx]) == 1
        # Its regression target is ~0 and weighted.
        assert np.allclose(t.bbox_targets[idx], 0.0, atol=1e-5)
        assert np.allclose(t.bbox_weights[idx], 1.0)

    def test_outside_anchors_ignored(self):
        gt, gtv = pad_gt([[10, 10, 100, 100]])
        t = assign_anchor(self.anchors, gt, gtv, self.im_info, self.key)
        a = np.asarray(self.anchors)
        outside = ~(
            (a[:, 0] >= 0) & (a[:, 1] >= 0) & (a[:, 2] < 256) & (a[:, 3] < 256)
        )
        assert np.all(np.asarray(t.labels)[outside] == -1)
        assert np.all(np.asarray(t.bbox_weights)[outside] == 0)

    def test_batch_size_cap(self):
        gt, gtv = pad_gt([[10, 10, 100, 100], [120, 120, 240, 240]])
        t = assign_anchor(
            self.anchors, gt, gtv, self.im_info, self.key, rpn_batch_size=256
        )
        labels = np.asarray(t.labels)
        assert (labels >= 0).sum() <= 256
        assert (labels == 1).sum() <= 128
        assert (labels == 1).sum() >= 1  # best-per-gt guarantee

    def test_no_gt_all_background(self):
        gt, gtv = pad_gt([])
        t = assign_anchor(self.anchors, gt, gtv, self.im_info, self.key)
        labels = np.asarray(t.labels)
        assert (labels == 1).sum() == 0
        # All inside anchors become negatives, capped at the 256 batch size.
        a = np.asarray(self.anchors)
        inside = (
            (a[:, 0] >= 0) & (a[:, 1] >= 0) & (a[:, 2] < 256) & (a[:, 3] < 256)
        ).sum()
        assert (labels == 0).sum() == min(inside, 256)

    def test_jit_matches_eager(self):
        gt, gtv = pad_gt([[10, 10, 100, 100]])
        f = lambda k: assign_anchor(self.anchors, gt, gtv, self.im_info, k)
        eager = f(self.key)
        jitted = jax.jit(f)(self.key)
        assert np.array_equal(eager.labels, jitted.labels)
        assert np.allclose(eager.bbox_targets, jitted.bbox_targets)


class TestSampleRois:
    NUM_CLASSES = 5

    def _run(self, rois, roi_valid, gts, classes, key=0, **kw):
        g = 8
        gt, gtv = pad_gt(gts, g)
        cls = np.zeros((g,), np.int32)
        cls[: len(classes)] = classes
        return sample_rois(
            jnp.array(rois, jnp.float32),
            jnp.array(roi_valid),
            gt,
            jnp.array(cls),
            gtv,
            jax.random.PRNGKey(key),
            num_classes=self.NUM_CLASSES,
            batch_rois=16,
            **kw,
        )

    def test_gt_appended_as_fg(self):
        # No proposals overlap gt, but the appended gt itself is a perfect fg.
        rois = np.array([[200, 200, 220, 220]] * 4, np.float32)
        valid = np.ones(4, bool)
        s = self._run(rois, valid, [[10, 10, 50, 50]], [3])
        labels = np.asarray(s.labels)
        fg = np.asarray(s.fg_mask)
        assert fg.sum() >= 1
        assert np.all(labels[fg] == 3)
        # fg rois are the gt box itself.
        assert np.allclose(np.asarray(s.rois)[fg][0], [10, 10, 50, 50])

    def test_fg_fraction_cap(self):
        # All 20 proposals identical to gt -> fg candidates abound; cap at 25%.
        rois = np.tile(np.array([[10, 10, 50, 50]], np.float32), (20, 1))
        s = self._run(rois, np.ones(20, bool), [[10, 10, 50, 50]], [2])
        assert np.asarray(s.fg_mask).sum() == 4  # 0.25 * 16
        assert np.asarray(s.labels)[np.asarray(s.fg_mask)].tolist() == [2] * 4

    def test_bg_labels_zero_weights_zero(self):
        rois = np.array([[200, 200, 240, 240]] * 10, np.float32)
        s = self._run(rois, np.ones(10, bool), [[10, 10, 50, 50]], [1])
        labels = np.asarray(s.labels)
        bg = np.asarray(s.valid) & ~np.asarray(s.fg_mask)
        assert np.all(labels[bg] == 0)
        w = np.asarray(s.bbox_weights)
        assert np.all(w[bg] == 0)

    def test_target_normalization_and_expansion(self):
        rois = np.array([[10, 10, 50, 50]], np.float32)  # exact gt match
        s = self._run(
            rois, np.ones(1, bool), [[10, 10, 50, 50]], [2],
            bbox_means=(0.1, 0.1, 0.1, 0.1), bbox_stds=(0.2, 0.2, 0.2, 0.2),
        )
        fg = np.asarray(s.fg_mask)
        t = np.asarray(s.bbox_targets)[fg][0].reshape(self.NUM_CLASSES, 4)
        # Raw delta 0 -> normalized (0 - 0.1)/0.2 = -0.5, only in class-2 block.
        assert np.allclose(t[2], -0.5, atol=1e-5)
        assert np.allclose(t[[0, 1, 3, 4]], 0.0)
        w = np.asarray(s.bbox_weights)[fg][0].reshape(self.NUM_CLASSES, 4)
        assert np.allclose(w[2], 1.0)
        assert np.allclose(w[[0, 1, 3, 4]], 0.0)

    def test_respects_roi_validity(self):
        # Invalid proposals must never be sampled even if they overlap gt.
        rois = np.tile(np.array([[10, 10, 50, 50]], np.float32), (6, 1))
        valid = np.zeros(6, bool)
        s = self._run(rois, valid, [[10, 10, 50, 50]], [1])
        # Only the appended gt can be fg.
        assert np.asarray(s.fg_mask).sum() == 1

    def test_jit_matches_eager(self):
        rois = np.random.RandomState(1).uniform(0, 200, (12, 4)).astype(np.float32)
        rois[:, 2:] += rois[:, :2]
        gt, gtv = pad_gt([[10, 10, 80, 80]], 8)
        cls = jnp.array([1] + [0] * 7, jnp.int32)
        valid = jnp.ones(12, bool)

        def f(k):
            return sample_rois(
                jnp.array(rois), valid, gt, cls, gtv, k,
                num_classes=self.NUM_CLASSES, batch_rois=16,
            )

        key = jax.random.PRNGKey(3)
        eager, jitted = f(key), jax.jit(f)(key)
        assert np.array_equal(eager.labels, jitted.labels)
        assert np.allclose(eager.bbox_targets, jitted.bbox_targets)
