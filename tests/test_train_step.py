"""Train-step integration: full fwd+bwd+update on an 8-device CPU mesh.

The DP analog of the reference's multi-GPU path (MutableModule over a context
list + KVStore 'device' allreduce) — SURVEY.md §5 says test it on
host-simulated devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models.faster_rcnn import build_model, forward_train, init_params
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.train.optimizer import build_optimizer, trainable_mask
from mx_rcnn_tpu.train.step import create_train_state, make_train_step

PAD = 128


def tiny_cfg(batch_images=1):
    return generate_config(
        "resnet50", "synthetic",
        **{
            "train.rpn_pre_nms_top_n": 256,
            "train.rpn_post_nms_top_n": 64,
            "train.batch_rois": 32,
            "train.max_gt_boxes": 8,
            "train.batch_images": batch_images,
            # Small anchors so some are inside the tiny test image.
            "network.anchor_scales": (2, 4, 8),
            "image.pad_shape": (PAD, PAD),
        },
    )


def tiny_batch(b):
    rs = np.random.RandomState(3)
    gt = np.zeros((b, 8, 4), np.float32)
    gt[:, 0] = [10, 10, 70, 60]
    gt[:, 1] = [50, 40, 110, 100]
    valid = np.zeros((b, 8), bool)
    valid[:, :2] = True
    classes = np.zeros((b, 8), np.int32)
    classes[:, :2] = [1, 3]
    return {
        "image": jnp.asarray(rs.randn(b, PAD, PAD, 3).astype(np.float32)),
        "im_info": jnp.asarray([[PAD, PAD, 1.0]] * b, np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    return cfg, model, params


def test_forward_train_losses_finite_and_nonzero(setup):
    cfg, model, params = setup
    loss, aux = jax.jit(
        lambda p, b, k: forward_train(model, p, b, k, cfg)
    )(params, tiny_batch(1), jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    # With small anchors the RPN must see positives and negatives.
    assert float(aux["rpn_cls_loss"]) > 0
    assert float(aux["rcnn_cls_loss"]) > 0


def test_train_step_updates_trainable_only(setup):
    cfg, model, params = setup
    tx = build_optimizer(cfg, params, steps_per_epoch=100)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=None, donate=False)
    new_state, metrics = step_fn(state, tiny_batch(1), jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["TotalLoss"]))

    mask = trainable_mask(params, cfg.network.fixed_param_patterns)
    flat_old = jax.tree_util.tree_leaves_with_path(params)
    flat_new = dict(jax.tree_util.tree_leaves_with_path(new_state.params))
    flat_mask = dict(jax.tree_util.tree_leaves_with_path(mask))
    changed_any = False
    for path, old in flat_old:
        new = flat_new[path]
        trainable = flat_mask[path]
        if not trainable:
            np.testing.assert_array_equal(
                np.asarray(old), np.asarray(new),
                err_msg=f"frozen param changed: {path}")
        elif not np.allclose(np.asarray(old), np.asarray(new)):
            changed_any = True
    assert changed_any, "no trainable parameter changed"


def test_frozen_trunk_with_live_grads_stays_fixed():
    """Freeze via optimizer mask where grads are NONZERO (no stop_gradient
    cut): the alternate-training stages 4/6 case. optax.masked would pass
    raw gradients through as updates here (gradient ascent on the 'frozen'
    trunk — the bug test_stages caught); the optimizer must hard-zero
    them."""
    from dataclasses import replace

    cfg = tiny_cfg()
    cfg = cfg.with_updates(network=replace(
        cfg.network, norm="group", freeze_at=0,
        fixed_param_patterns=("features",)))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))

    # Sanity: grads through the trunk really are nonzero in this config.
    # (jitted: the eager 128^2 backward costs ~30 s of tier-1 wall time.)
    grads = jax.jit(jax.grad(lambda p: forward_train(
        model, p, tiny_batch(1), jax.random.PRNGKey(2), cfg)[0]))(params)
    g = grads["params"]["features"]["stage3"]["block0"]["conv1"]["kernel"]
    assert float(jnp.abs(g).max()) > 0.0

    tx = build_optimizer(cfg, params, steps_per_epoch=100)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=None, donate=False)
    new_state, _ = step_fn(state, tiny_batch(1), jax.random.PRNGKey(2))
    old = params["params"]["features"]["stage3"]["block0"]["conv1"]["kernel"]
    new = new_state.params["params"]["features"]["stage3"]["block0"]["conv1"]["kernel"]
    np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
    # ...while the heads trained.
    assert not np.array_equal(
        np.asarray(params["params"]["rpn"]["rpn_conv"]["kernel"]),
        np.asarray(new_state.params["params"]["rpn"]["rpn_conv"]["kernel"]))


def test_adamw_optimizer_knob():
    """train.optimizer='adamw' (the DETR/ViTDet preset): builds, steps,
    and still hard-zeros frozen leaves."""
    from dataclasses import replace

    cfg = tiny_cfg()
    cfg = cfg.with_updates(train=replace(cfg.train, optimizer="adamw",
                                         lr=1e-4, clip_gradient=0.1))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=None, donate=False)
    new_state, metrics = step_fn(state, tiny_batch(1), jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["TotalLoss"]))
    # frozen stem stays fixed under adamw too
    old = params["params"]["features"]["conv0"]["kernel"]
    new = new_state.params["params"]["features"]["conv0"]["kernel"]
    np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
    # trainable heads moved
    assert not np.array_equal(
        np.asarray(params["params"]["rpn"]["rpn_conv"]["kernel"]),
        np.asarray(new_state.params["params"]["rpn"]["rpn_conv"]["kernel"]))

    with pytest.raises(ValueError, match="sgd.*adamw|adamw.*sgd"):
        bad = cfg.with_updates(train=replace(cfg.train, optimizer="lion"))
        build_optimizer(bad, params)


def test_transformer_presets_use_adamw():
    from mx_rcnn_tpu.config import generate_config as gc

    assert gc("detr_r50", "coco").train.optimizer == "adamw"
    assert gc("vitdet_b", "coco").train.optimizer == "adamw"
    assert gc("resnet101", "coco").train.optimizer == "sgd"
    assert gc("resnet101_fpn", "coco").train.optimizer == "sgd"


def test_frozen_mask_covers_reference_prefixes(setup):
    cfg, model, params = setup
    mask = trainable_mask(params, cfg.network.fixed_param_patterns)
    flat = jax.tree_util.tree_leaves_with_path(mask)

    def joined(path):
        return "/".join(str(getattr(p, "key", p)) for p in path)

    for path, trainable in flat:
        j = joined(path)
        if "conv0" in j or "stage1" in j or "bn0" in j:
            assert not trainable, f"{j} should be frozen"
        if j.endswith("gamma") or j.endswith("beta"):
            assert not trainable, f"{j} (BN affine) should be frozen"
        if "rpn" in j or "cls_score" in j or "bbox_pred" in j:
            assert trainable, f"{j} should be trainable"


def test_multichip_dp_step_runs():
    """8-device CPU mesh: batch sharded, grads allreduced, one step."""
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    cfg = tiny_cfg(batch_images=8)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    mesh = create_mesh("8")
    tx = build_optimizer(cfg, params, steps_per_epoch=100)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=mesh, donate=False)
    batch = shard_batch(tiny_batch(8), mesh)
    new_state, metrics = step_fn(state, batch, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["TotalLoss"]))


def test_dp_grads_match_single_device():
    """DP over 2 virtual devices == single device on the same 2-image batch
    (the KVStore-allreduce correctness check the reference never had).

    Tolerance is split by jax generation instead of xfail'ing: on
    pre-varying-type jax (< 0.5) the old partitioner's bf16 reduction
    order drifts the DP loss ~0.2% — within the borderline of the tight
    rtol, so a non-strict xfail sometimes XPASSed, and the driver's
    `^[.FEsx]+` dot grep drops uppercase-`X` lines (the dot count
    flapped). A 1% gate on old jax still catches real allreduce breakage
    (wrong psum semantics are order-1 errors) and the outcome is
    deterministic; newer XLA keeps the calibrated tight gate."""
    cfg = tiny_cfg(batch_images=2)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(2)
    rng = jax.random.PRNGKey(5)

    tx = build_optimizer(cfg, params, steps_per_epoch=100)
    s1 = create_train_state(params, tx)
    single = make_train_step(model, cfg, mesh=None, donate=False)
    s1_new, m1 = single(s1, batch, rng)

    mesh = create_mesh("2")
    s2 = create_train_state(params, tx)
    dp = make_train_step(model, cfg, mesh=mesh, donate=False)
    s2_new, m2 = dp(s2, shard_batch(batch, mesh), rng)

    old_jax = (not hasattr(jax.lax, "pvary")
               and not hasattr(jax.lax, "pcast"))
    loss_rtol = 1e-2 if old_jax else 1e-4
    param_rtol, param_atol = (1e-2, 2e-4) if old_jax else (2e-3, 2e-5)
    assert np.allclose(float(m1["TotalLoss"]), float(m2["TotalLoss"]),
                       rtol=loss_rtol)
    l1 = jax.tree.leaves(s1_new.params)
    l2 = jax.tree.leaves(s2_new.params)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=param_rtol, atol=param_atol)


def test_remat_matches_no_remat():
    """network.remat=True (jax.checkpoint on ResNet stages) must give the
    same loss and gradients as the plain backbone, with an identical
    parameter tree (checkpoints are interchangeable)."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import zoo

    def cfg_for(remat):
        return generate_config("resnet50", "synthetic", **{
            "image.pad_shape": (128, 128),
            "network.norm": "group",
            "network.freeze_at": 0,
            "network.remat": remat,
            "network.anchor_scales": (2, 4, 8),
            "train.rpn_pre_nms_top_n": 256,
            "train.rpn_post_nms_top_n": 64,
            "train.batch_rois": 16,
            "train.max_gt_boxes": 8,
        })

    rs = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rs.randn(1, 128, 128, 3).astype(np.float32)),
        "im_info": jnp.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": jnp.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": jnp.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": jnp.asarray([[True, True] + [False] * 6]),
    }
    cfg_plain, cfg_remat = cfg_for(False), cfg_for(True)
    model_plain = zoo.build_model(cfg_plain)
    model_remat = zoo.build_model(cfg_remat)
    params = zoo.init_params(model_plain, cfg_plain, jax.random.PRNGKey(0))
    # identical parameter tree -> same params load into the remat model
    params_r = zoo.init_params(model_remat, cfg_remat, jax.random.PRNGKey(0))
    assert jax.tree.structure(params) == jax.tree.structure(params_r)

    key = jax.random.PRNGKey(1)

    def loss_fn(model, cfg):
        return lambda p: zoo.forward_train(model, p, batch, key, cfg)[0]

    # jit both graphs: eager per-op dispatch of the 128^2 fwd+bwd costs
    # ~45 s of tier-1 wall time; the jitted pair rides the persistent
    # compile cache (same numerics — the parity being gated).
    l_plain, g_plain = jax.jit(
        jax.value_and_grad(loss_fn(model_plain, cfg_plain)))(params)
    l_remat, g_remat = jax.jit(
        jax.value_and_grad(loss_fn(model_remat, cfg_remat)))(params)
    assert np.isclose(float(l_plain), float(l_remat), rtol=1e-5)
    flat_p = jax.tree.leaves(g_plain)
    flat_r = jax.tree.leaves(g_remat)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-4, atol=1e-5)


def _accum_cfg(**train_over):
    """64^2 micro-config: the accum tests compile fresh f32 graphs, so
    every shape is minimized (the 128^2 version costs ~45 min on CPU)."""
    from dataclasses import replace

    cfg = generate_config(
        "resnet50", "synthetic",
        **{
            "train.rpn_pre_nms_top_n": 128,
            "train.rpn_post_nms_top_n": 32,
            "train.batch_rois": 16,
            "train.max_gt_boxes": 4,
            "train.batch_images": 1,
            "network.anchor_scales": (2, 4),
            "image.pad_shape": (64, 64),
        })
    return cfg.with_updates(
        train=replace(cfg.train, **{"compute_dtype": "f32",
                                    "grad_accum_steps": 2, **train_over}))


def _accum_batch(b):
    rs = np.random.RandomState(3)
    gt = np.zeros((b, 4, 4), np.float32)
    gt[:, 0] = [8, 8, 40, 40]
    valid = np.zeros((b, 4), bool)
    valid[:, 0] = True
    classes = np.zeros((b, 4), np.int32)
    classes[:, 0] = 1
    return {
        "image": jnp.asarray(rs.randn(b, 64, 64, 3).astype(np.float32)),
        "im_info": jnp.asarray([[64, 64, 1.0]] * b, np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }


def test_grad_accum_matches_manual_average():
    """accum=2 over a 2-image batch reproduces (g0 + g1)/2 applied once —
    the unrolled micro-step loop is an exact re-ordering of the big-batch
    gradient math."""
    cfg = _accum_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    batch = _accum_batch(2)
    rng = jax.random.PRNGKey(11)

    accum_step = make_train_step(model, cfg, donate=False)
    new_state, metrics = accum_step(
        create_train_state(params, tx), batch, rng)
    assert np.isfinite(float(metrics["TotalLoss"]))

    # Manual: per-chunk grads with the same split keys, averaged, applied.
    keys = jax.random.split(rng, 2)

    @jax.jit
    def grads_of(chunk, key):
        def loss_fn(p):
            loss, _ = forward_train(model, p, chunk, key, cfg)
            return loss

        return jax.grad(loss_fn)(params)

    chunk = lambda i: {k: v[i:i + 1] for k, v in batch.items()}
    g = jax.tree.map(lambda a, b: (a + b) / 2,
                     grads_of(chunk(0), keys[0]),
                     grads_of(chunk(1), keys[1]))
    manual = create_train_state(params, tx).apply_gradients(g)

    flat_a = jax.tree_util.tree_leaves(new_state.params)
    flat_m = jax.tree_util.tree_leaves(manual.params)
    for a, b in zip(flat_a, flat_m):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_grad_accum_under_dp_mesh():
    """accum=2 composes with the data mesh (the reshaped micro-batch axis
    reshards; semantics hold)."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = _accum_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    mesh = create_mesh("2")
    step = make_train_step(model, cfg, mesh=mesh, donate=False)
    # accum(2) x data(2) x batch_images(1) = 4 images per optimizer step.
    state, metrics = step(create_train_state(params, tx),
                          shard_batch(_accum_batch(4), mesh),
                          jax.random.PRNGKey(5))
    assert np.isfinite(float(metrics["TotalLoss"]))


def test_opt_state_dtype_bf16_slots():
    """train.opt_state_dtype=bfloat16 stores the momentum slot in bf16
    (HBM lever, PERF.md r4) and still trains: one step moves params and
    the bf16-slot trajectory tracks the f32 one closely."""
    import jax.numpy as jnp

    cfg32 = _accum_cfg(grad_accum_steps=1)
    cfg16 = _accum_cfg(grad_accum_steps=1, opt_state_dtype="bfloat16")
    model = build_model(cfg32)
    params = init_params(model, cfg32, jax.random.PRNGKey(0))
    batch = _accum_batch(1)
    rng = jax.random.PRNGKey(3)

    outs = {}
    for tag, cfg in (("f32", cfg32), ("bf16", cfg16)):
        tx = build_optimizer(cfg, params, steps_per_epoch=10)
        state = create_train_state(params, tx)
        if tag == "bf16":
            dtypes = {l.dtype for l in jax.tree.leaves(state.opt_state)
                      if hasattr(l, "dtype") and l.ndim > 0}
            assert jnp.dtype(jnp.bfloat16) in dtypes, dtypes
        step = make_train_step(model, cfg, donate=False)
        state, m = step(state, batch, rng)
        outs[tag] = (state, float(m["TotalLoss"]))
    assert np.isfinite(outs["bf16"][1])
    np.testing.assert_allclose(outs["bf16"][1], outs["f32"][1], rtol=1e-4)
    a = jax.tree.leaves(outs["bf16"][0].params)[0]
    b = jax.tree.leaves(outs["f32"][0].params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_multi_step_dispatch_matches_sequential_steps():
    """multi_step_dispatch=2 over step-stacked batches reproduces two
    sequential single-step dispatches exactly (same per-step rng split),
    with metrics pooled across the K steps."""
    cfg1 = _accum_cfg(grad_accum_steps=1)
    cfgK = _accum_cfg(grad_accum_steps=1, multi_step_dispatch=2)
    model = build_model(cfg1)
    params = init_params(model, cfg1, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg1, params, steps_per_epoch=10)
    rng = jax.random.PRNGKey(11)
    b0, b1 = _accum_batch(1), _accum_batch(1)
    b1 = {**b1, "image": b1["image"] + 0.5}  # distinct step payloads

    multi_step = make_train_step(model, cfgK, donate=False)
    stacked = {k: jnp.stack([b0[k], b1[k]]) for k in b0}
    state_k, metrics_k = multi_step(
        create_train_state(params, tx), stacked, rng)

    single_step = make_train_step(model, cfg1, donate=False)
    keys = jax.random.split(rng, 2)
    state_s = create_train_state(params, tx)
    state_s, m0 = single_step(state_s, b0, keys[0])
    state_s, m1 = single_step(state_s, b1, keys[1])

    assert int(state_k.step) == 2
    for a, b in zip(jax.tree.leaves(state_k.params),
                    jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        float(metrics_k["TotalLoss"]),
        (float(m0["TotalLoss"]) + float(m1["TotalLoss"])) / 2, rtol=1e-5)


@pytest.mark.slow
def test_multi_step_dispatch_under_dp_mesh():
    """multi_step_dispatch composes with the data mesh: stacked batches
    shard P(None, 'data') and the scan carries the updated state.

    slow: lax.scan over the full fwd+bwd under a mesh is the SPMD
    partitioner's worst case (same pathology the grad-accum unroll note
    in train/step.py documents) — ~20 min of compile on CPU. The no-mesh
    exactness test + the mesh-'1' fit smoke cover the semantics in the
    fast suite."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = _accum_cfg(grad_accum_steps=1, multi_step_dispatch=2)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    mesh = create_mesh("2")
    step = make_train_step(model, cfg, mesh=mesh, donate=False)
    stacked = {k: jnp.stack([v, v]) for k, v in _accum_batch(2).items()}
    state, metrics = step(create_train_state(params, tx),
                          shard_batch(stacked, mesh, stacked=True),
                          jax.random.PRNGKey(5))
    assert int(state.step) == 2
    assert np.isfinite(float(metrics["TotalLoss"]))


@pytest.mark.slow
def test_multi_step_dispatch_composes_with_grad_accum():
    """multi=2 x accum=2: each scanned step consumes an accum-reshaped
    batch and performs ONE update from 2 micro-grads — 2 updates per
    dispatch over 4 images, equal to running the accum step twice.
    (slow: scan body holds the unrolled double fwd+bwd — heavy compile.)"""
    cfgA = _accum_cfg()  # accum=2, multi=1
    cfgAM = _accum_cfg(multi_step_dispatch=2)  # accum=2, multi=2
    model = build_model(cfgA)
    params = init_params(model, cfgA, jax.random.PRNGKey(0))
    tx = build_optimizer(cfgA, params, steps_per_epoch=10)
    rng = jax.random.PRNGKey(9)
    b0, b1 = _accum_batch(2), _accum_batch(2)
    b1 = {**b1, "image": b1["image"] + 0.25}

    multi_step = make_train_step(model, cfgAM, donate=False)
    stacked = {k: jnp.stack([b0[k], b1[k]]) for k in b0}
    state_m, metrics_m = multi_step(
        create_train_state(params, tx), stacked, rng)

    single = make_train_step(model, cfgA, donate=False)
    keys = jax.random.split(rng, 2)
    state_s = create_train_state(params, tx)
    state_s, _ = single(state_s, b0, keys[0])
    state_s, _ = single(state_s, b1, keys[1])

    assert int(state_m.step) == 2
    for a, b in zip(jax.tree.leaves(state_m.params),
                    jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert np.isfinite(float(metrics_m["TotalLoss"]))


def test_multi_step_dispatch_fit_smoke(tmp_path):
    """fit_detector groups the loader stream into K-step dispatches and
    drops the trailing partial group."""
    from dataclasses import replace

    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = _accum_cfg(grad_accum_steps=1, multi_step_dispatch=2,
                     flip=False, lr_step=(100,))
    cfg = cfg.with_updates(
        image=replace(cfg.image, scales=((64, 64),)))
    ds = SyntheticDataset("train", num_images=5, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    history = []
    fit_detector(cfg, ds.gt_roidb(), prefix=str(tmp_path / "msd"),
                 end_epoch=1, frequent=1000, seed=0, mesh_spec="1",
                 epoch_callback=lambda e, s, b: history.append(
                     (int(s.step), b.get()["TotalLoss"])))
    # 5 loader batches → 2 dispatches of 2 steps; 1 dropped.
    assert len(history) == 1 and history[0][0] == 4, history
    assert np.isfinite(history[0][1])


def test_grad_accum_fit_smoke(tmp_path):
    """fit_detector sizes the loader at accum x batch_images and trains."""
    from dataclasses import replace

    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = _accum_cfg(flip=False, lr_step=(100,))
    cfg = cfg.with_updates(
        image=replace(cfg.image, scales=((64, 64),)))
    ds = SyntheticDataset("train", num_images=4, image_size=64,
                          max_objects=1, min_size_frac=3, max_size_frac=2)
    history = []
    fit_detector(cfg, ds.gt_roidb(), prefix=str(tmp_path / "ga"),
                 end_epoch=1, frequent=1000, seed=0,
                 epoch_callback=lambda e, s, b: history.append(
                     b.get()["TotalLoss"]))
    assert len(history) == 1 and np.isfinite(history).all(), history
