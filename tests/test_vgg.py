"""VGG-16 backbone paths (models/backbones.py VGGConv/VGGHead + the vgg
branches of models/faster_rcnn.py).

Reference: rcnn/symbol/symbol_vgg.py — get_vgg_train/test and the
get_vgg_rpn*/get_vgg_rcnn* alternate-stage variants; the reference's
headline VOC number (70.2 mAP @0.5) is a VGG-16 number, so these paths
must be executed, not just present. Tiny shapes: grads through fc6/fc7
(25088×4096) are the expensive part; one step each is enough to pin
finiteness + the frozen conv1-2 cut + dropout determinism wiring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models.backbones import VGGConv, VGGHead
from mx_rcnn_tpu.models.faster_rcnn import (
    build_model,
    forward_test,
    forward_train,
    forward_train_rcnn,
    forward_train_rpn,
    init_params,
)

PAD = 128

TINY = {
    "train.rpn_pre_nms_top_n": 256,
    "train.rpn_post_nms_top_n": 64,
    "train.batch_rois": 32,
    "train.max_gt_boxes": 8,
    "train.batch_images": 1,
    "network.anchor_scales": (2, 4, 8),
    "image.pad_shape": (PAD, PAD),
    "test.rpn_pre_nms_top_n": 128,
    "test.rpn_post_nms_top_n": 32,
}


@pytest.fixture(scope="module")
def vgg_setup():
    cfg = generate_config("vgg", "synthetic", **TINY)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    return cfg, model, params


def tiny_batch(with_proposals=False):
    rs = np.random.RandomState(3)
    gt = np.zeros((1, 8, 4), np.float32)
    gt[:, 0] = [10, 10, 70, 60]
    gt[:, 1] = [50, 40, 110, 100]
    valid = np.zeros((1, 8), bool)
    valid[:, :2] = True
    classes = np.zeros((1, 8), np.int32)
    classes[:, :2] = [1, 3]
    batch = {
        "image": jnp.asarray(rs.randn(1, PAD, PAD, 3).astype(np.float32)),
        "im_info": jnp.asarray([[PAD, PAD, 1.0]], np.float32),
        "gt_boxes": jnp.asarray(gt),
        "gt_classes": jnp.asarray(classes),
        "gt_valid": jnp.asarray(valid),
    }
    if with_proposals:
        props = np.zeros((1, 16, 4), np.float32)
        props[0, :3] = [[8, 8, 72, 62], [48, 38, 112, 102], [0, 0, 60, 60]]
        pvalid = np.zeros((1, 16), bool)
        pvalid[0, :3] = True
        batch["proposals"] = jnp.asarray(props)
        batch["proposal_valid"] = jnp.asarray(pvalid)
    return batch


def test_vgg_conv_shape_and_freeze():
    """13-conv trunk → stride-16 512-ch features; conv1-2 frozen via the
    stop_gradient cut (reference fixed_param_prefix=['conv1','conv2'])."""
    model = VGGConv(freeze_blocks=2)
    # nonzero input: zeros make every activation (and so every kernel
    # grad) exactly 0, which would vacuously pass the frozen checks
    x = jax.random.normal(jax.random.PRNGKey(42), (1, 64, 64, 3))
    params = model.init(jax.random.PRNGKey(0), x)
    y, grads = jax.value_and_grad(
        lambda p: model.apply(p, x).astype(jnp.float32).sum())(params)
    feat = model.apply(params, x)
    assert feat.shape == (1, 4, 4, 512)
    g = grads["params"]
    for frozen in ("conv1_1", "conv1_2", "conv2_1", "conv2_2"):
        assert float(jnp.abs(g[frozen]["kernel"]).max()) == 0.0, frozen
    for live in ("conv3_1", "conv5_3"):
        assert float(jnp.abs(g[live]["kernel"]).max()) > 0.0, live


def test_vgg_head_dropout_wiring():
    """fc6/fc7 4096 head; dropout active only when deterministic=False
    (reference: DropOut in get_vgg_train only)."""
    model = VGGHead()
    x = jnp.ones((2, 7, 7, 512))
    params = model.init(jax.random.PRNGKey(0), x)
    det = model.apply(params, x, deterministic=True)
    assert det.shape == (2, 4096)
    det2 = model.apply(params, x, deterministic=True)
    np.testing.assert_array_equal(np.asarray(det), np.asarray(det2))
    stoch = model.apply(params, x, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(1)})
    assert not np.array_equal(np.asarray(det), np.asarray(stoch))


@pytest.mark.parametrize("fwd,needs_proposals", [
    (forward_train, False),        # get_vgg_train (end2end)
    (forward_train_rpn, False),    # get_vgg_rpn (alternate stages 1/4)
    (forward_train_rcnn, True),    # get_vgg_rcnn (alternate stages 3/6)
])
def test_vgg_train_variants_finite_loss_and_grads(vgg_setup, fwd,
                                                  needs_proposals):
    cfg, model, params = vgg_setup
    batch = tiny_batch(with_proposals=needs_proposals)

    def loss_fn(p):
        loss, aux = fwd(model, p, batch, jax.random.PRNGKey(1), cfg)
        return loss, aux

    (loss, aux), grads = jax.jit(
        jax.value_and_grad(loss_fn, has_aux=True))(params)
    assert np.isfinite(float(loss)), fwd.__name__
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    # frozen conv1-2 must receive zero grads in every variant
    g = grads["params"]["features"]
    assert float(jnp.abs(g["conv1_1"]["kernel"]).max()) == 0.0


def test_vgg_test_forward(vgg_setup):
    cfg, model, params = vgg_setup
    batch = tiny_batch()
    rois, roi_valid, scores, boxes = jax.jit(
        lambda p, im, info: forward_test(model, p, im, info, cfg)
    )(params, batch["image"], batch["im_info"])
    n = cfg.test.rpn_post_nms_top_n
    c = cfg.dataset.num_classes
    assert scores.shape == (1, n, c)
    assert boxes.shape == (1, n, 4 * c)
    assert np.isfinite(np.asarray(scores)).all()
    assert np.isfinite(np.asarray(boxes)).all()


def test_vgg_from_scratch_unfreezes_conv12():
    """freeze_at=0 (--from-scratch) must train the WHOLE VGG net: the
    conv1-2 stop_gradient cut lifts AND the optimizer mask drops the
    conv1_1..conv2_2 patterns — otherwise the stem stays at random init
    for the entire run (one knob, one freeze)."""
    from dataclasses import replace

    from mx_rcnn_tpu.train.optimizer import (
        build_optimizer,
        effective_fixed_patterns,
    )
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = generate_config("vgg", "synthetic", **TINY)
    cfg = cfg.with_updates(network=replace(cfg.network, freeze_at=0))
    assert not any(p.startswith(("conv1_", "conv2_"))
                   for p in effective_fixed_patterns(cfg))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg, mesh=None, donate=False)
    new_state, _ = step_fn(state, tiny_batch(), jax.random.PRNGKey(2))
    old = params["params"]["features"]["conv1_1"]["kernel"]
    new = new_state.params["params"]["features"]["conv1_1"]["kernel"]
    assert not np.array_equal(np.asarray(old), np.asarray(new)), \
        "conv1_1 did not train under freeze_at=0"


@pytest.mark.slow
def test_vgg_fit_smoke(tmp_path):
    """Short synthetic fit through fit_detector — the full train loop
    (loader → jitted step → checkpoint) on the VGG graph."""
    from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.train import fit_detector

    cfg = generate_config("vgg", "synthetic", **dict(TINY, **{
        "image.scales": ((PAD, PAD),),
        "train.rpn_positive_overlap": 0.5,
        "train.flip": False,
        "train.lr": 0.001,
        "train.lr_step": (100,),
    }))
    ds = SyntheticDataset("train", num_images=4, image_size=PAD,
                          max_objects=2, min_size_frac=4, max_size_frac=2)
    roidb = ds.gt_roidb()
    history = []
    fit_detector(cfg, roidb, prefix=str(tmp_path / "ckpt"), end_epoch=2,
                 frequent=1000, seed=0,
                 epoch_callback=lambda e, s, b: history.append(
                     b.get()["TotalLoss"]))
    assert len(history) == 2
    assert np.isfinite(history).all(), history
