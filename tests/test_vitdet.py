"""ViTDet (models/vit.py): backbone, SFP, detector forwards, ring option.

BASELINE.json config 5 (stretch). The reference has no transformer models
(SURVEY.md §3.2); semantics follow Li et al. (ViTDet) as documented in the
module. The detector reuses the fpn.py functional forwards via the shared
pyramid method surface (models/zoo.py duck dispatch).
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.compile_heavy

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import zoo
from mx_rcnn_tpu.models.vit import SimpleFeaturePyramid, ViTBackbone, ViTDet
from mx_rcnn_tpu.parallel.mesh import create_mesh


def tiny_cfg(mask=False, **overrides):
    base = {
        "image.pad_shape": (128, 128),
        "train.batch_images": 1,
        "network.vit_dim": 32,
        "network.vit_depth": 2,
        "network.vit_heads": 2,
        "network.vit_window": 4,
        "train.fpn_rpn_pre_nms_per_level": 64,
        "train.rpn_post_nms_top_n": 64,
        "train.batch_rois": 32,
        "train.max_gt_boxes": 8,
        "train.mask_gt_resolution": 28,
        "test.fpn_rpn_pre_nms_per_level": 32,
        "test.rpn_post_nms_top_n": 16,
    }
    base.update(overrides)
    return generate_config("vitdet_b_mask" if mask else "vitdet_b",
                           "synthetic", **base)


def tiny_batch(rng, mask=False):
    batch = {
        "image": rng.randn(1, 128, 128, 3).astype(np.float32),
        "im_info": np.asarray([[128, 128, 1.0]], np.float32),
        "gt_boxes": np.asarray(
            [[[10, 10, 60, 90], [70, 20, 120, 70]] + [[0, 0, 0, 0]] * 6],
            np.float32),
        "gt_classes": np.asarray([[1, 2] + [0] * 6], np.int32),
        "gt_valid": np.asarray([[True, True] + [False] * 6]),
    }
    if mask:
        gm = np.zeros((1, 8, 28, 28), np.uint8)
        gm[0, :2, 6:22, 6:22] = 1
        batch["gt_masks"] = gm
    return batch


def test_backbone_shapes_and_window_padding(rng):
    # 80x112 image -> 5x7 token grid: not divisible by window 4, exercises
    # the window pad/unpad path.
    vit = ViTBackbone(patch=16, dim=32, depth=2, heads=2, window=4,
                      dtype=jnp.float32)
    x = jnp.asarray(rng.randn(1, 80, 112, 3).astype(np.float32))
    params = vit.init(jax.random.PRNGKey(0), x)
    out = vit.apply(params, x)
    assert out.shape == (1, 5, 7, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_sfp_levels(rng):
    sfp = SimpleFeaturePyramid(channels=16, dtype=jnp.float32)
    feat = jnp.asarray(rng.randn(1, 8, 8, 32).astype(np.float32))
    params = sfp.init(jax.random.PRNGKey(0), feat)
    out = sfp.apply(params, feat)
    assert set(out.keys()) == {2, 3, 4, 5, 6}
    assert out[2].shape == (1, 32, 32, 16)
    assert out[3].shape == (1, 16, 16, 16)
    assert out[4].shape == (1, 8, 8, 16)
    assert out[5].shape == (1, 4, 4, 16)
    assert out[6].shape == (1, 2, 2, 16)


def test_forward_train_and_test(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    assert isinstance(model, ViTDet)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    rois, rv, scores, boxes = jax.jit(
        lambda p, i, ii: zoo.forward_test(model, p, i, ii, cfg)
    )(params, batch["image"], batch["im_info"])
    r, c = cfg.test.rpn_post_nms_top_n, cfg.dataset.num_classes
    assert rois.shape == (1, r, 4)
    assert scores.shape == (1, r, c)
    assert boxes.shape == (1, r, 4 * c)


def test_grads_reach_vit(rng):
    cfg = tiny_cfg()
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    grads = jax.jit(jax.grad(
        lambda p: zoo.forward_train(model, p, batch,
                                    jax.random.PRNGKey(1), cfg)[0]))(params)
    flat = jax.tree_util.tree_flatten_with_path(grads)[0]

    def norm_of(substr):
        return sum(float(jnp.sum(jnp.abs(leaf)))
                   for path, leaf in flat
                   if substr in jax.tree_util.keystr(path))

    for part in ("patch_embed", "block0", "block1", "neck", "rpn",
                 "cls_score"):
        assert norm_of(part) > 0, f"no gradient reached {part}"


def test_mask_variant(rng):
    cfg = tiny_cfg(mask=True)
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    batch = tiny_batch(rng, mask=True)
    loss, aux = jax.jit(
        lambda p, b, r: zoo.forward_train(model, p, b, r, cfg)
    )(params, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert np.isfinite(float(aux["mask_loss"]))


def test_ring_attention_matches_dense(rng):
    """ViTDet with ring attention over a 4-way model axis == dense."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    cfg = tiny_cfg(**{"network.use_ring_attention": True})
    mesh = create_mesh("1x4")
    model_ring = zoo.build_model(cfg, mesh=mesh)
    cfg_dense = cfg.with_updates(
        network=replace(cfg.network, use_ring_attention=False))
    model_dense = zoo.build_model(cfg_dense)
    params = zoo.init_params(model_dense, cfg_dense, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    key = jax.random.PRNGKey(1)
    l_ring, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_ring, p, b, r, cfg))(params, batch, key)
    l_dense, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_dense, p, b, r, cfg_dense))(params, batch, key)
    assert np.isclose(float(l_ring), float(l_dense), rtol=1e-4)


def test_train_step_under_dp_mesh(rng):
    """One ViTDet train step over a 2-way data mesh (the dryrun shape)."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    from mx_rcnn_tpu.parallel.mesh import shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    cfg = tiny_cfg(**{"train.batch_images": 2})
    model = zoo.build_model(cfg)
    params = zoo.init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=10)
    state = create_train_state(params, tx)
    mesh = create_mesh("2")
    step = make_train_step(model, cfg, mesh=mesh,
                           forward_fn=zoo.forward_train, donate=False)
    one = tiny_batch(rng)
    batch = {k: np.repeat(v, 2, axis=0) for k, v in one.items()}
    state, metrics = step(state, shard_batch(batch, mesh),
                          jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["TotalLoss"]))


def test_window_block_nondivisible_grid(rng):
    """Window attention pads a 5x7 grid to 8x8 windows and unpads exactly;
    small depths make every BACKBONE block global, so the window path is
    pinned here at the Block level."""
    from mx_rcnn_tpu.models.vit import Block

    blk = Block(dim=16, heads=2, window=4, dtype=jnp.float32)
    x = jnp.asarray(rng.randn(2, 5, 7, 16).astype(np.float32))
    params = blk.init(jax.random.PRNGKey(0), x)
    out = blk.apply(params, x)
    assert out.shape == (2, 5, 7, 16)
    assert np.isfinite(np.asarray(out)).all()


def test_global_block_pattern_vitb():
    """Depth 12 → globals end each quarter: blocks 2, 5, 8, 11 (ViTDet)."""
    depth = 12
    global_blocks = {depth * k // 4 - 1 for k in range(1, 5)}
    assert global_blocks == {2, 5, 8, 11}


def test_ulysses_attention_matches_dense(rng):
    """ViTDet with all-to-all (Ulysses) SP over a 2-way model axis ==
    dense (network.sp_mode='ulysses'; tiny_cfg has 2 heads, so the axis
    size must divide 2)."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    # float32 end-to-end: the Ulysses op is exact, but bf16 attention
    # rounding can flip discrete top-k/NMS selections on some platforms,
    # making an rtol comparison of the post-selection losses flaky.
    cfg = tiny_cfg(**{"network.use_ring_attention": True,
                      "network.sp_mode": "ulysses",
                      "train.compute_dtype": "f32"})
    mesh = create_mesh("1x2")
    model_sp = zoo.build_model(cfg, mesh=mesh)
    cfg_dense = cfg.with_updates(
        network=replace(cfg.network, use_ring_attention=False,
                        sp_mode="ring"))
    model_dense = zoo.build_model(cfg_dense)
    params = zoo.init_params(model_dense, cfg_dense, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    key = jax.random.PRNGKey(1)
    l_sp, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_sp, p, b, r, cfg))(params, batch, key)
    l_dense, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_dense, p, b, r, cfg_dense))(params, batch, key)
    assert np.isclose(float(l_sp), float(l_dense), rtol=1e-4)


def test_streaming_attn_impl_matches_dense(rng):
    """network.attn_impl='streaming' routes the global blocks through the
    flash-style streaming-softmax kernel with identical numerics (r5; a
    small kv_chunk forces a real multi-block scan at tiny token counts)."""
    cfg = tiny_cfg(**{"network.attn_impl": "streaming",
                      "network.attn_kv_chunk": 8})
    model_s = zoo.build_model(cfg)
    cfg_d = cfg.with_updates(
        network=replace(cfg.network, attn_impl="dense"))
    model_d = zoo.build_model(cfg_d)
    params = zoo.init_params(model_d, cfg_d, jax.random.PRNGKey(0))
    batch = tiny_batch(rng)
    key = jax.random.PRNGKey(1)
    l_s, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_s, p, b, r, cfg))(params, batch, key)
    l_d, _ = jax.jit(lambda p, b, r: zoo.forward_train(
        model_d, p, b, r, cfg_d))(params, batch, key)
    assert np.isclose(float(l_s), float(l_d), rtol=1e-4), (l_s, l_d)


def test_attn_impl_unknown_value_raises():
    """network.attn_impl outside {'dense','streaming'} fails at build
    time for every family (mirrors the sp_mode validation) instead of
    being silently treated as dense (advisor r5)."""
    bad = generate_config("resnet50", "synthetic",
                          **{"network.attn_impl": "flash"})
    with pytest.raises(ValueError, match="attn_impl"):
        zoo.build_model(bad)


def test_attn_impl_streaming_superseded_by_sp_warns(caplog):
    """'streaming' + a sequence-parallel build: the SP kernels manage
    their own attention, so the knob is accepted with a supersede
    warning (mirrors the pp_stages warning)."""
    import logging

    cfg = tiny_cfg(**{"network.attn_impl": "streaming",
                      "network.use_ring_attention": True})
    mesh = create_mesh("1x2")
    with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
        zoo.build_model(cfg, mesh=mesh)
    assert any("superseded" in r.getMessage() for r in caplog.records)
