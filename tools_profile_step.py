"""Ad-hoc: time each stage of the train step on the real chip."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models.faster_rcnn import (
    FasterRCNN, _assign_anchors_batch, _backbone_rpn, _pool_rois, _rpn_softmax,
    build_model, forward_train, init_params)
from mx_rcnn_tpu.ops.anchors import anchor_grid
from mx_rcnn_tpu.ops.proposal import generate_proposals
from mx_rcnn_tpu.targets.rcnn_targets import sample_rois
from functools import partial

cfg = generate_config("resnet101", "coco",
                      **{"image.pad_shape": (640, 1024), "train.batch_images": 1})
b, (h, w), g = 1, cfg.image.pad_shape, cfg.train.max_gt_boxes
rs = np.random.RandomState(0)
boxes = np.zeros((b, g, 4), np.float32)
boxes[:, :8] = np.stack([
    rs.uniform(0, w - 200, (b, 8)), rs.uniform(0, h - 200, (b, 8)),
    rs.uniform(200, 400, (b, 8)), rs.uniform(200, 400, (b, 8))], axis=-1)
valid = np.zeros((b, g), bool); valid[:, :8] = True
classes = np.zeros((b, g), np.int32); classes[:, :8] = 5
batch = {
    "image": jnp.asarray(rs.randn(b, h, w, 3).astype(np.float32)),
    "im_info": jnp.asarray([[600, 1000, 1.0]] * b, np.float32),
    "gt_boxes": jnp.asarray(boxes), "gt_classes": jnp.asarray(classes),
    "gt_valid": jnp.asarray(valid),
}
model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(0))
rng = jax.random.PRNGKey(1)


def timeit(name, fn, *args, n=5):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    print(f"{name:30s} {(time.perf_counter() - t0) / n * 1e3:9.1f} ms")
    return out


feat_fn = jax.jit(lambda p, im: _backbone_rpn(model, p, im, cfg))
feat, cls_l, box_d, anchors = timeit("backbone+rpn fwd", feat_fn, params, batch["image"])

assign_fn = jax.jit(lambda a, bt, r: _assign_anchors_batch(a, bt, r, cfg))
timeit("assign_anchor", assign_fn, anchors, batch, rng)

prop_fn = jax.jit(lambda cl, bd, ii: generate_proposals(
    _rpn_softmax(cl, model.num_anchors), bd, ii, anchors,
    pre_nms_top_n=cfg.train.rpn_pre_nms_top_n,
    post_nms_top_n=cfg.train.rpn_post_nms_top_n,
    nms_thresh=cfg.train.rpn_nms_thresh,
    min_size=cfg.train.rpn_min_size, feat_stride=16))
rois, roi_valid, _ = timeit("generate_proposals(train)", prop_fn, cls_l, box_d, batch["im_info"])

samp_fn = jax.jit(lambda r, v, bt, k: jax.vmap(partial(
    sample_rois, num_classes=model.num_classes, batch_rois=cfg.train.batch_rois,
    fg_fraction=cfg.train.fg_fraction, fg_thresh=cfg.train.fg_thresh,
    bg_thresh_hi=cfg.train.bg_thresh_hi, bg_thresh_lo=cfg.train.bg_thresh_lo,
    bbox_means=cfg.train.bbox_means, bbox_stds=cfg.train.bbox_stds))(
    r, v, bt["gt_boxes"], bt["gt_classes"], bt["gt_valid"],
    jax.random.split(k, r.shape[0])))
samples = timeit("sample_rois", samp_fn, rois, roi_valid, batch, rng)

pool_fn = jax.jit(lambda f, r, v: _pool_rois(f, r, v, model.roi_pool_size,
                                             model.roi_pool_type))
pooled = timeit("roi_align", pool_fn, feat, samples.rois, samples.valid)

head_fn = jax.jit(lambda p, x: model.apply(p, x, True, method=FasterRCNN.box_head))
timeit("box_head fwd", head_fn, params, pooled)

fwd = jax.jit(lambda p, bt, r: forward_train(model, p, bt, r, cfg)[0])
timeit("full fwd", fwd, params, batch, rng, n=3)

grad = jax.jit(jax.grad(lambda p, bt, r: forward_train(model, p, bt, r, cfg)[0]))
timeit("full fwd+bwd", grad, params, batch, rng, n=3)
