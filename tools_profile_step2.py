import time
import jax, jax.numpy as jnp, numpy as np
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models.faster_rcnn import build_model, init_params
from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
from mx_rcnn_tpu.train.optimizer import build_optimizer
from mx_rcnn_tpu.train.step import create_train_state, make_train_step

cfg = generate_config("resnet101", "coco",
                      **{"image.pad_shape": (640, 1024), "train.batch_images": 1})
b, (h, w), g = 1, cfg.image.pad_shape, cfg.train.max_gt_boxes
rs = np.random.RandomState(0)
boxes = np.zeros((b, g, 4), np.float32); boxes[:, :8] = [100, 100, 300, 300]
valid = np.zeros((b, g), bool); valid[:, :8] = True
classes = np.zeros((b, g), np.int32); classes[:, :8] = 5
batch = {"image": rs.randn(b, h, w, 3).astype(np.float32),
         "im_info": np.asarray([[600, 1000, 1.0]] * b, np.float32),
         "gt_boxes": boxes, "gt_classes": classes, "gt_valid": valid}
model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(0))
tx = build_optimizer(cfg, params, steps_per_epoch=1000)
state = create_train_state(params, tx)
mesh = create_mesh(str(jax.device_count()))
step_fn = make_train_step(model, cfg, mesh=mesh)
batch = shard_batch(batch, mesh)
rng = jax.random.PRNGKey(1)
t0 = time.perf_counter()
state, metrics = step_fn(state, batch, rng)
jax.block_until_ready(metrics["TotalLoss"])
print(f"compile+first step: {time.perf_counter()-t0:.1f}s")
for it in range(5):
    rng, k = jax.random.split(rng)
    t0 = time.perf_counter()
    state, metrics = step_fn(state, batch, k)
    jax.block_until_ready(metrics["TotalLoss"])
    print(f"step {it}: {(time.perf_counter()-t0)*1e3:.0f} ms")
