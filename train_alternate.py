"""4-stage alternate optimization (Ren et al. 2015).

Reference entry point: train_alternate.py (SURVEY.md §4.4):
  1. train RPN (from pretrained trunk)
  2. dump stage-1 proposals
  3. train Fast R-CNN on them (fresh trunk)
  4. train RPN again, trunk frozen from stage 3
  5. dump stage-2 proposals
  6. train Fast R-CNN, trunk frozen
  7. combine RPN(4) + RCNN(6) → final checkpoint

    python train_alternate.py --network vgg --dataset PascalVOC \
        --image_set 2007_trainval --prefix model/alt
"""

from __future__ import annotations

import argparse
import os

import jax

from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.tools.stages import (
    test_rpn_generate,
    train_rcnn,
    train_rpn,
)
from mx_rcnn_tpu.train.checkpoint import save_checkpoint
from mx_rcnn_tpu.utils.combine_model import combine_model


def parse_args():
    p = argparse.ArgumentParser(description="Alternate-optimization training")
    p.add_argument("--network", default="vgg")
    p.add_argument("--dataset", default="PascalVOC")
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/alt")
    p.add_argument("--rpn_epoch", type=int, default=8)
    p.add_argument("--rcnn_epoch", type=int, default=8)
    p.add_argument("--frequent", type=int, default=20)
    p.add_argument("--tpu-mesh", "--gpus", dest="tpu_mesh", default="")
    return p.parse_args()


def alternate_train(cfg, prefix, rpn_epoch, rcnn_epoch, mesh_spec="",
                    frequent=20):
    os.makedirs(prefix, exist_ok=True)
    logger.info("=== stage 1: train RPN ===")
    rpn1 = train_rpn(cfg, f"{prefix}_rpn1", end_epoch=rpn_epoch,
                     mesh_spec=mesh_spec, frequent=frequent)
    logger.info("=== stage 2: generate stage-1 proposals ===")
    _, recalls1 = test_rpn_generate(cfg, rpn1, f"{prefix}_rpn1_proposals.pkl")
    logger.info("stage-1 RPN proposal recall: %s", recalls1)
    logger.info("=== stage 3: train Fast R-CNN ===")
    rcnn1 = train_rcnn(cfg, f"{prefix}_rcnn1", f"{prefix}_rpn1_proposals.pkl",
                       end_epoch=rcnn_epoch, mesh_spec=mesh_spec,
                       frequent=frequent)
    logger.info("=== stage 4: re-train RPN, trunk frozen ===")
    rpn2 = train_rpn(cfg, f"{prefix}_rpn2", pretrained_params=rcnn1,
                     end_epoch=rpn_epoch, frozen_trunk=True,
                     mesh_spec=mesh_spec, frequent=frequent)
    logger.info("=== stage 5: generate stage-2 proposals ===")
    _, recalls2 = test_rpn_generate(cfg, rpn2, f"{prefix}_rpn2_proposals.pkl")
    logger.info("stage-2 RPN proposal recall: %s", recalls2)
    logger.info("=== stage 6: re-train Fast R-CNN, trunk frozen ===")
    rcnn2 = train_rcnn(cfg, f"{prefix}_rcnn2", f"{prefix}_rpn2_proposals.pkl",
                       pretrained_params=rpn2, end_epoch=rcnn_epoch,
                       frozen_trunk=True, mesh_spec=mesh_spec,
                       frequent=frequent)
    logger.info("=== stage 7: combine ===")
    final = combine_model(rpn2, rcnn2)
    save_checkpoint(prefix, 0, final,
                    means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
                    num_classes=cfg.dataset.num_classes)
    logger.info("alternate training complete: %s", prefix)
    return final


def main():
    enable_persistent_cache()
    args = parse_args()
    overrides = {}
    if args.image_set:
        overrides["dataset.image_set"] = args.image_set
    if args.root_path:
        overrides["dataset.root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset.dataset_path"] = args.dataset_path
    cfg = generate_config(args.network, args.dataset, **overrides)
    alternate_train(cfg, args.prefix, args.rpn_epoch, args.rcnn_epoch,
                    mesh_spec=args.tpu_mesh, frequent=args.frequent)


if __name__ == "__main__":
    main()
