"""Train Faster R-CNN end-to-end (approximate joint optimization).

Reference entry point: train_end2end.py (flags preserved per the north star;
``--gpus`` → ``--tpu-mesh``, ``--kvstore`` kept as a no-op alias since the
mesh IS the comm backend). Example:

    python train_end2end.py --network resnet101 --dataset coco \
        --image_set train2017 --tpu-mesh 8 --prefix model/e2e
"""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache
from mx_rcnn_tpu.config import generate_config, parse_cli_overrides
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.tools.train import fit_detector, load_gt_roidbs


def parse_args():
    p = argparse.ArgumentParser(description="Train Faster R-CNN end-to-end")
    p.add_argument("--network", default="resnet101",
                   help="vgg | resnet50 | resnet101 | *_fpn | *_fpn_mask")
    p.add_argument("--dataset", default="coco",
                   help="PascalVOC | coco | synthetic")
    p.add_argument("--image_set", default=None,
                   help="e.g. 2007_trainval or train2017; '+' merges sets")
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--frequent", type=int, default=20, help="logging interval")
    p.add_argument("--kvstore", default="device",
                   help="no-op alias (comm backend is the TPU mesh)")
    p.add_argument("--work_load_list", default=None, help="no-op alias")
    p.add_argument("--no_flip", action="store_true")
    p.add_argument("--no_shuffle", action="store_true")
    p.add_argument("--resume", nargs="?", const=True, default=False,
                   choices=[True, "auto"], metavar="auto",
                   help="bare --resume: restart from the latest epoch-"
                        "boundary checkpoint under --prefix; --resume auto "
                        "also picks up graftguard emergency (mid-epoch) "
                        "saves — the restart contract after a rc=75 "
                        "preemption exit (OUTAGES.md)")
    p.add_argument("--pretrained", default=None,
                   help="init weights: a .npz ImageNet manifest (see "
                        "utils/pretrained.py; convert torch checkpoints "
                        "with utils/torch_convert.py) or an orbax "
                        "checkpoint prefix")
    p.add_argument("--pretrained_epoch", type=int, default=0)
    p.add_argument("--prefix", default="model/e2e", help="checkpoint prefix")
    p.add_argument("--begin_epoch", type=int, default=0)
    p.add_argument("--end_epoch", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_step", default=None, help="e.g. '7' or '5,7'")
    p.add_argument("--tpu-mesh", "--gpus", dest="tpu_mesh", default="",
                   help="mesh shape: '8' or '4x2' (replaces --gpus)")
    p.add_argument("--from-scratch", dest="from_scratch", action="store_true",
                   help="no pretrained weights: GroupNorm backbone, no "
                        "frozen prefix (frozen-BN with identity statistics "
                        "is unstable — see models/backbones.py). The "
                        "matching test.py run needs the same flag.")
    p.add_argument("--set", dest="set_cfg", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="dotted config override, repeatable — e.g. "
                        "--set network.tensor_parallel=true "
                        "--set train.batch_images=2 (values parsed as "
                        "python literals / bool words, else kept as strings)")
    p.add_argument("--packed-dir", dest="packed_dir", default=None,
                   help="train from packed pre-decoded shards written by "
                        "tools/pack_dataset.py (data/packed.py) instead "
                        "of decoding JPEGs per epoch — the host "
                        "input-pipeline fast path (PERF.md r4)")
    return p.parse_args()


def main():
    enable_persistent_cache()
    # Multi-host (dist_sync analog): connect BEFORE any jax device use.
    from mx_rcnn_tpu.parallel.distributed import maybe_initialize_distributed
    maybe_initialize_distributed()

    args = parse_args()
    overrides = {}
    if args.image_set:
        overrides["dataset.image_set"] = args.image_set
    if args.root_path:
        overrides["dataset.root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset.dataset_path"] = args.dataset_path
    if args.no_flip:
        overrides["train.flip"] = False
    if args.no_shuffle:
        overrides["train.shuffle"] = False
    if args.lr is not None:
        overrides["train.lr"] = args.lr
    if args.lr_step:
        overrides["train.lr_step"] = tuple(
            int(s) for s in args.lr_step.split(","))
    if args.end_epoch:
        overrides["train.end_epoch"] = args.end_epoch
    if args.from_scratch:
        overrides["network.norm"] = "group"
        overrides["network.freeze_at"] = 0
    overrides.update(parse_cli_overrides(args.set_cfg))
    cfg = generate_config(args.network, args.dataset, **overrides)
    logger.info("config: network=%s dataset=%s", args.network, args.dataset)

    pretrained = None
    pretrained_npz = None
    if args.pretrained and args.pretrained.endswith(".npz"):
        pretrained_npz = args.pretrained  # ImageNet manifest (merged in fit)
    elif args.pretrained:
        from mx_rcnn_tpu.train.checkpoint import load_checkpoint
        pretrained, _ = load_checkpoint(
            args.pretrained, args.pretrained_epoch,
            means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
            num_classes=cfg.dataset.num_classes)

    if args.packed_dir:
        # No dataset construction here: a training host may hold ONLY the
        # packed shards (the point of packing) — flip is roidb bookkeeping.
        from mx_rcnn_tpu.data.datasets.imdb import (
            append_flipped_roidb, filter_roidb)
        from mx_rcnn_tpu.data.packed import load_packed_roidb

        roidb = load_packed_roidb(args.packed_dir, cfg)
        if cfg.train.flip:
            roidb = append_flipped_roidb(roidb, name=args.packed_dir)
        roidb = filter_roidb(roidb)
    else:
        roidb = load_gt_roidbs(cfg)
    fit_detector(
        cfg, roidb, args.prefix,
        begin_epoch=args.begin_epoch,
        end_epoch=args.end_epoch,
        frequent=args.frequent,
        resume=args.resume,
        pretrained_params=pretrained,
        pretrained_npz=pretrained_npz,
        mesh_spec=args.tpu_mesh,
    )


if __name__ == "__main__":
    main()
